"""Auto-parallel planner tests (framework/auto_parallel.py +
costs.strategy_is_feasible, ISSUE 15).

Five disciplines:
1. one unit test per NAMED rejection branch of strategy_is_feasible —
   the compile-free twin of every executor/pass gate;
2. planner properties — deterministic for a fixed seed, every emitted
   strategy is in the feasible set (representative builders in tier-1,
   the full MODEL_BUILDERS x mesh sweep slow-marked), HBM budget
   rejection, pinned-mesh planning;
3. plan-aware memory pricing (costs.predict with strategy.memory_plan)
   and the ledger identity staying green on a planned cell;
4. executor adoption — BuildStrategy.auto_parallel chooses strategy +
   mesh with fixed-seed parity vs single device, PTPU_AUTO_PARALLEL
   kill switch reverts to the user's config;
5. re-plan on elastic resize — dp2 -> dp4 restore re-plans
   deterministically, prices both restore layouts, and keeps fixed-seed
   parity vs BOTH the kept-strategy restore and the uninterrupted run;
   plus the committed BENCH_PLAN artifact's checks (planner matches or
   beats the best hand-picked strategy; never predicts-better-but-
   measures-worse beyond the band).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core import flags as _flags
from paddle_tpu.core.enforce import InvalidArgumentError
from paddle_tpu.framework import auto_parallel, costs
from paddle_tpu.framework.auto_parallel import (StrategyPoint,
                                                mesh_factorizations)
from paddle_tpu.parallel import ParallelExecutor, annotate_tp, elastic
from paddle_tpu.parallel.mesh import DeviceMesh
from paddle_tpu.parallel.strategy import (BuildStrategy,
                                          GradientScaleStrategy,
                                          ReduceStrategy)

import test_static_analysis as _tsa  # pytest puts tests/ on sys.path

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mlp_program(in_dim=64):
    x = layers.data("x", shape=[in_dim])
    label = layers.data("label", shape=[1], dtype="int64")
    h = layers.fc(x, size=128, act="relu")
    loss = layers.mean(layers.softmax_with_cross_entropy(
        layers.fc(h, size=10), label))
    pt.optimizer.MomentumOptimizer(0.1, momentum=0.9).minimize(loss)
    return pt.default_main_program(), loss


def _rs(**kw):
    bst = BuildStrategy(**kw)
    bst.reduce_strategy = ReduceStrategy.ReduceScatter
    return bst


def _codes(feas):
    return feas.reason_codes()


# ---------------------------------------------------------------------------
# 1. named rejection branches
# ---------------------------------------------------------------------------


class TestFeasibilityRejections:
    def test_feasible_deep_returns_rewritten_program(self):
        prog, _ = _mlp_program()
        f = costs.strategy_is_feasible(prog, _rs(), mesh_axes={"dp": 4},
                                       nominal_batch=16)
        assert f.ok and not f.reasons
        assert getattr(f.program, "_dp_comm_applied", False)
        # the input program is untouched
        assert not getattr(prog, "_dp_comm_applied", False)

    def test_shallow_check_skips_the_rewrites(self):
        prog, _ = _mlp_program()
        f = costs.strategy_is_feasible(prog, _rs(), mesh_axes={"dp": 4},
                                       nominal_batch=16, deep=False)
        assert f.ok and f.program is None

    def test_quant_invalid(self):
        prog, _ = _mlp_program()
        bst = BuildStrategy()
        bst.quant_comm = "fp4"
        f = costs.strategy_is_feasible(prog, bst, mesh_axes={"dp": 2})
        assert _codes(f) == ["quant-invalid"]

    def test_gradient_scale_unsupported(self):
        prog, _ = _mlp_program()
        bst = BuildStrategy(
            gradient_scale_strategy=GradientScaleStrategy.CoeffNumDevice)
        f = costs.strategy_is_feasible(prog, bst, mesh_axes={"dp": 2})
        assert "gradient-scale-unsupported" in _codes(f)

    def test_mesh_mismatch_pp_axis(self):
        prog, _ = _mlp_program()
        f = costs.strategy_is_feasible(
            prog, BuildStrategy(pipeline_stages=2, num_microbatches=4),
            mesh_axes={"dp": 4}, nominal_batch=16)
        assert "mesh-mismatch" in _codes(f)
        # and the inverse: a pp axis the strategy does not ask for
        f2 = costs.strategy_is_feasible(prog, BuildStrategy(),
                                        mesh_axes={"dp": 2, "pp": 2})
        assert "mesh-mismatch" in _codes(f2)

    def test_batch_indivisible_explicit(self):
        prog, _ = _mlp_program()
        f = costs.strategy_is_feasible(prog, _rs(), mesh_axes={"dp": 4},
                                       nominal_batch=6)
        assert _codes(f) == ["batch-indivisible"]

    def test_batch_indivisible_pipeline(self):
        prog, _ = _mlp_program()
        f = costs.strategy_is_feasible(
            prog, BuildStrategy(pipeline_stages=2, num_microbatches=4),
            mesh_axes={"dp": 2, "pp": 2}, nominal_batch=12)
        assert _codes(f) == ["batch-indivisible"]

    def test_batch_norm(self):
        x = layers.data("x", shape=[8])
        h = layers.batch_norm(layers.fc(x, size=8))
        loss = layers.mean(h)
        pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
        f = costs.strategy_is_feasible(pt.default_main_program(), _rs(),
                                       mesh_axes={"dp": 2})
        assert _codes(f) == ["batch-norm"]

    def test_non_mean_loss(self):
        x = layers.data("x", shape=[8])
        loss = layers.reduce_sum(layers.fc(x, size=4))
        pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
        f = costs.strategy_is_feasible(pt.default_main_program(), _rs(),
                                       mesh_axes={"dp": 2})
        assert _codes(f) == ["non-mean-loss"]

    def test_sp_manual_conflict(self):
        prog, _ = _mlp_program()
        bst = _rs(enable_sequence_parallel=True)
        f = costs.strategy_is_feasible(prog, bst, mesh_axes={"dp": 2})
        assert _codes(f) == ["sp-manual-conflict"]

    def test_multi_region(self):
        x = layers.data("x", shape=[8])
        l1 = layers.mean(layers.fc(x, size=4))
        l2 = layers.mean(layers.fc(x, size=4))
        pt.optimizer.SGD(learning_rate=0.1).minimize(l1)
        pt.optimizer.SGD(learning_rate=0.1).minimize(l2)
        f = costs.strategy_is_feasible(
            pt.default_main_program(),
            BuildStrategy(pipeline_stages=2, num_microbatches=4),
            mesh_axes={"dp": 1, "pp": 2}, nominal_batch=16)
        assert "multi-region" in _codes(f)

    def test_pp_too_few_ops(self):
        x = layers.data("x", shape=[8])
        loss = layers.mean(layers.fc(x, size=4))
        pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
        f = costs.strategy_is_feasible(
            pt.default_main_program(),
            BuildStrategy(pipeline_stages=4, num_microbatches=4),
            mesh_axes={"dp": 1, "pp": 4}, nominal_batch=16)
        assert "pp-too-few-ops" in _codes(f)

    def test_narrow_cut(self):
        """Twenty parallel branches all read at the end: the balanced
        partition's cut crosses more than max_boundary_vars activations
        — the DEEP check maps pipeline_partition_pass's narrow-cut
        enforce to its named reason."""
        x = layers.data("x", shape=[16])
        branches = [layers.fc(x, size=4, act="relu") for _ in range(20)]
        acc = branches[0]
        for b in branches[1:]:
            acc = layers.elementwise_add(acc, b)
        loss = layers.mean(acc)
        pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
        f = costs.strategy_is_feasible(
            pt.default_main_program(),
            BuildStrategy(pipeline_stages=2, num_microbatches=4),
            mesh_axes={"dp": 1, "pp": 2}, nominal_batch=16)
        assert not f.ok
        assert set(_codes(f)) <= {"narrow-cut", "pp-gate"}
        assert "narrow-cut" in _codes(f)

    def test_tp_unannotated(self):
        prog, _ = _mlp_program()
        f = costs.strategy_is_feasible(prog, _rs(),
                                       mesh_axes={"dp": 2, "tp": 2})
        assert _codes(f) == ["tp-unannotated"]

    def test_tp_indivisible(self):
        x = layers.data("x", shape=[6])
        h = layers.fc(x, size=6, act="relu")
        loss = layers.mean(layers.fc(h, size=3))
        pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
        prog = pt.default_main_program()
        # annotate a weight whose sharded dim does not divide by tp=4
        for b in prog.blocks:
            for v in b.vars.values():
                if getattr(v, "trainable", False) and v.shape == (6, 6):
                    v.sharding_spec = (None, "tp")
        f = costs.strategy_is_feasible(prog, _rs(),
                                       mesh_axes={"dp": 1, "tp": 4})
        assert "tp-indivisible" in _codes(f)

    def test_non_tp_sharded_param(self):
        prog, _ = _mlp_program()
        for b in prog.blocks:
            for v in b.vars.values():
                if getattr(v, "trainable", False) and v.shape and \
                        len(v.shape) == 2:
                    v.sharding_spec = ("dp", None)
                    break
        f = costs.strategy_is_feasible(prog, _rs(), mesh_axes={"dp": 2})
        assert "non-tp-sharded-param" in _codes(f)


# ---------------------------------------------------------------------------
# 2./3. step model + plan-aware memory pricing
# ---------------------------------------------------------------------------


def _transformer_program(tp_annotate=False):
    from paddle_tpu.models import transformer
    loss, _ = transformer.transformer_lm(
        vocab=128, max_len=32, d_model=64, d_inner=128, num_heads=4,
        num_layers=2, dropout=0.0, mean_loss=True)
    if tp_annotate:
        assert annotate_tp()
    pt.optimizer.AdamOptimizer(1e-3).minimize(loss)
    return pt.default_main_program(), loss


class TestStepModel:
    def test_breakdown_sections(self):
        prog, _ = _mlp_program()
        f = costs.strategy_is_feasible(prog, _rs(), mesh_axes={"dp": 4},
                                       nominal_batch=16)
        rep = costs.predict(f.program, _rs(), dp=4, nominal_batch=16)
        sec = costs.predicted_step_seconds(rep, mesh_axes={"dp": 4},
                                           strategy=_rs())
        assert sec["total_s"] > 0
        assert sec["compute_s"] > 0 and sec["dp_comm_s"] > 0
        assert sec["launch_s"] > 0 and sec["bubble_s"] == 0
        assert sec["total_s"] == pytest.approx(sum(
            v for k, v in sec.items()
            if k.endswith("_s") and k != "total_s"))

    def test_pipeline_bubble_priced(self):
        prog, _ = _mlp_program()
        bst = BuildStrategy(pipeline_stages=2, num_microbatches=4)
        f = costs.strategy_is_feasible(prog, bst,
                                       mesh_axes={"dp": 2, "pp": 2},
                                       nominal_batch=16)
        rep = costs.predict(f.program, bst, dp=2, nominal_batch=16)
        sec = costs.predicted_step_seconds(
            rep, mesh_axes={"dp": 2, "pp": 2}, strategy=bst)
        assert sec["bubble_s"] > 0 and sec["pp_comm_s"] > 0
        # (M+K-1)/M with M=4, K=2: bubble = compute * 0.25
        assert sec["bubble_s"] == pytest.approx(sec["compute_s"] * 0.25)

    def test_unsharded_tp_axis_not_credited(self):
        """A tp mesh axis the rewrite shards nothing over must not
        divide predicted compute (the dp1-tp4 'free lunch' loophole)."""
        prog, _ = _mlp_program()
        rep = costs.predict(prog, None, dp=1, nominal_batch=16)
        sec4 = costs.predicted_step_seconds(rep,
                                            mesh_axes={"dp": 1, "tp": 4})
        sec1 = costs.predicted_step_seconds(rep, mesh_axes={"dp": 1})
        assert sec4["compute_s"] == sec1["compute_s"]

    def test_quant_priced_against_hbm(self):
        prog, _ = _mlp_program()
        q = _rs()
        q.quant_comm = "int8"
        f = costs.strategy_is_feasible(prog, q, mesh_axes={"dp": 4},
                                       nominal_batch=16)
        rep = costs.predict(f.program, q, dp=4, nominal_batch=16)
        sec = costs.predicted_step_seconds(rep, mesh_axes={"dp": 4},
                                           strategy=q)
        assert sec["quant_s"] > 0

    def test_spmd_zero1_wire_costs_more_than_allreduce(self):
        """The Reduce mode's XLA lowering all-gathers the sharded-update
        params ON TOP of the gradient all-reduce (census-measured) — the
        planner must not price it as plain allreduce."""
        prog, _ = _mlp_program()
        bst_r = BuildStrategy(reduce_strategy=ReduceStrategy.Reduce)
        rep_r = costs.predict(prog, bst_r, dp=4, nominal_batch=16)
        rep_ar = costs.predict(prog, BuildStrategy(), dp=4,
                               nominal_batch=16)
        assert rep_r["dp_comm"]["wire_bytes"] > \
            rep_ar["dp_comm"]["wire_bytes"]
        assert rep_r["dp_comm"].get("exact") is False


class TestPlanAwareMemoryPricing:
    def test_predict_prices_the_plan_when_strategy_sets_it(self):
        prog, _ = _transformer_program()
        bst = _rs(memory_plan=True)
        f = costs.strategy_is_feasible(prog, bst, mesh_axes={"dp": 2},
                                       nominal_batch=32)
        assert f.ok and getattr(f.program, "_memory_plan_applied", False)
        rep = costs.predict(f.program, bst, dp=2, nominal_batch=32)
        per_dev = rep["memory"]["per_device"]
        assert "transient_peak_planned" in per_dev
        # this transformer's remat plan frees real stash (the run_ci
        # memory-plan stanza pins the measured reduction on the same
        # shape) — the PLANNED transient must be strictly below
        assert per_dev["transient_peak_planned"] < \
            per_dev["transient_peak"]
        assert rep["memory"]["planned_peak_total_bytes"] < \
            rep["memory"]["peak_total_bytes"]
        assert costs.predicted_device_bytes(rep, planned=True) < \
            costs.predicted_device_bytes(rep, planned=False)

    def test_unplanned_predict_has_no_planned_keys(self):
        prog, _ = _transformer_program()
        bst = _rs()
        f = costs.strategy_is_feasible(prog, bst, mesh_axes={"dp": 2},
                                       nominal_batch=32)
        rep = costs.predict(f.program, bst, dp=2, nominal_batch=32)
        assert "transient_peak_planned" not in rep["memory"]["per_device"]
        assert "planned_peak_total_bytes" not in rep["memory"]

    def test_ledger_identity_stays_green_on_planned_cell(self):
        """The planned pricing rides NEW keys only: the ledger's exact
        per-category checks and residual bound must hold unchanged on an
        executed memory-planned dp2 cell."""
        from paddle_tpu.observability.ledger import CostLedger
        rng = np.random.RandomState(0)
        _, loss = _mlp_program()
        bst = _rs(memory_plan=True)
        bst.memory_plan_time_budget_s = 1.0
        exe = ParallelExecutor(loss_name=loss.name, build_strategy=bst,
                               mesh=DeviceMesh(jax.devices()[:2],
                                               {"dp": 2}))
        pt.Executor().run(pt.default_startup_program())
        feed = {"x": rng.rand(16, 64).astype("float32"),
                "label": rng.randint(0, 10, (16, 1)).astype("int64")}
        jax.block_until_ready(exe.run(feed=feed, fetch_list=[loss],
                                      return_numpy=False))
        row = CostLedger("t").row("mnist_dp2_planned")
        row.set_prediction(exe.cost_report(nominal_batch=16))
        row.set_memory_census(exe.memory_census(feed=feed))
        row.check_memory_identity(residual_frac=0.10)
        assert row.ok, [c for c in row.checks if not c["ok"]]


# ---------------------------------------------------------------------------
# planner properties
# ---------------------------------------------------------------------------


class TestPlanner:
    def test_mesh_factorizations(self):
        f8 = mesh_factorizations(8)
        assert (8, 1, 1) == f8[0]
        assert (2, 2, 2) in f8 and (1, 8, 1) in f8 and (1, 1, 8) in f8
        assert all(dp * pp * tp == 8 for dp, pp, tp in f8)

    def test_canonicalization_dedupes_irrelevant_knobs(self):
        a = StrategyPoint(dp=4, microbatches=8, schedule="gpipe")
        assert a.canonical() == StrategyPoint(dp=4)
        b = StrategyPoint(dp=4, reduce="allreduce", quant="",
                          bucket_bytes=1 << 20)
        assert b.canonical().bucket_bytes == 4 << 20

    def test_plan_is_deterministic_for_fixed_seed(self):
        prog, _ = _mlp_program()
        r1 = auto_parallel.plan(prog, 4, nominal_batch=16, seed=3)
        r2 = auto_parallel.plan(prog, 4, nominal_batch=16, seed=3)
        assert r1.point == r2.point
        assert [row["point"] for row in r1.ranking] == \
            [row["point"] for row in r2.ranking]

    def test_chosen_strategy_is_feasible_and_adoptable(self):
        prog, _ = _mlp_program()
        r = auto_parallel.plan(prog, 4, nominal_batch=16)
        f = costs.strategy_is_feasible(prog, r.strategy,
                                       mesh_axes=r.mesh_axes,
                                       nominal_batch=16)
        assert f.ok
        assert r.n_feasible > 0 and r.predicted_step_s > 0
        assert r.rank_of(r.point) == 1

    def test_hbm_budget_rejects_everything_when_tiny(self):
        prog, _ = _mlp_program()
        with pytest.raises(InvalidArgumentError) as ei:
            auto_parallel.plan(prog, 4, nominal_batch=16, hbm_bytes=1)
        assert "hbm-budget" in str(ei.value)

    def test_pinned_mesh_dict_searches_only_the_other_knobs(self):
        prog, _ = _mlp_program()
        r = auto_parallel.plan(prog, {"dp": 2, "pp": 2},
                               nominal_batch=16)
        assert r.point.dp == 2 and r.point.pp == 2
        assert r.strategy.pipeline_stages == 2

    def test_numerics_preserving_space_pins_quant(self):
        base = _rs()
        base.quant_comm = "int8"
        sp = auto_parallel.numerics_preserving_space(base)
        assert sp.quant_modes == ("int8",)
        assert auto_parallel.numerics_preserving_space(
            BuildStrategy()).quant_modes == ("",)

    def test_pinned_quant_space_never_emits_unquantized_points(self):
        """A numerics-preserving space pinned to int8 must hold across
        the WHOLE search — grid and annealer both: an unquantized point
        would silently change the training numerics the pin exists to
        preserve (and vice versa for a pinned-'' base)."""
        prog, _ = _mlp_program()
        base = _rs()
        base.quant_comm = "int8"
        r = auto_parallel.plan(
            prog, 4, nominal_batch=16, strategy_base=base,
            space=auto_parallel.numerics_preserving_space(base))
        assert all(row["point"].quant == "int8" for row in r.ranking), \
            [row["point"].describe() for row in r.ranking[:6]]
        assert r.strategy.quant_comm == "int8"
        r2 = auto_parallel.plan(
            prog, 4, nominal_batch=16,
            space=auto_parallel.numerics_preserving_space(
                BuildStrategy()))
        assert all(row["point"].quant == "" for row in r2.ranking)

    #: representative builders for the tier-1 property: a plain mlp, a
    #: batch-norm model (manual modes gate-rejected), a recurrent net, a
    #: sparse-embedding recommender, and the tp-annotated transformer
    REPRESENTATIVE = ("mnist_mlp", "resnet_cifar10", "stacked_lstm",
                      "deepfm", "transformer_lm_tp")

    @pytest.mark.parametrize("name", REPRESENTATIVE)
    def test_planner_emits_feasible_strategies(self, name):
        loss = _tsa.MODEL_BUILDERS[name]()
        if loss is not None:
            pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
        prog = pt.default_main_program()
        r = auto_parallel.plan(prog, 4, nominal_batch=16,
                               anneal_iters=8)
        f = costs.strategy_is_feasible(prog, r.strategy,
                                       mesh_axes=r.mesh_axes,
                                       nominal_batch=16)
        assert f.ok, (name, r.point, f.reasons)

    @pytest.mark.slow
    @pytest.mark.parametrize("name", sorted(_tsa.MODEL_BUILDERS))
    @pytest.mark.parametrize("n_devices", (2, 4, 8))
    def test_planner_emits_feasible_strategies_full_sweep(self, name,
                                                          n_devices):
        loss = _tsa.MODEL_BUILDERS[name]()
        if loss is not None:
            pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
        prog = pt.default_main_program()
        r = auto_parallel.plan(prog, n_devices, nominal_batch=16,
                               anneal_iters=16)
        f = costs.strategy_is_feasible(prog, r.strategy,
                                       mesh_axes=r.mesh_axes,
                                       nominal_batch=16)
        assert f.ok, (name, n_devices, r.point, f.reasons)


# ---------------------------------------------------------------------------
# 4. executor adoption + kill switch
# ---------------------------------------------------------------------------


def _feeds(n, batch=16, cols=64):
    rng = np.random.RandomState(0)
    return [{"x": rng.rand(batch, cols).astype("float32"),
             "label": rng.randint(0, 10, (batch, 1)).astype("int64")}
            for _ in range(n)]


def _fresh_mlp():
    pt.reset_default_programs()
    pt.reset_global_scope()
    with pt.core.unique_name.guard():
        _, loss = _mlp_program()
    return loss


class TestExecutorAdoption:
    def test_auto_parallel_adopts_and_keeps_parity(self):
        feeds = _feeds(3)
        loss = _fresh_mlp()
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        base = [float(exe.run(feed=f, fetch_list=[loss])[0])
                for f in feeds]
        loss = _fresh_mlp()
        pexe = ParallelExecutor(
            loss_name=loss.name,
            build_strategy=BuildStrategy(auto_parallel=True),
            mesh=DeviceMesh(jax.devices()[:4], {"dp": 4}))
        pt.Executor().run(pt.default_startup_program())
        got = [float(pexe.run(feed=f, fetch_list=[loss])[0])
               for f in feeds]
        assert max(abs(a - b) for a, b in zip(base, got)) <= 1e-5
        rep = pexe.auto_plan_report()
        assert rep is not None and rep.point.describe()
        # the adopted strategy never flips lossy wire on implicitly
        assert pexe.build_strategy.quant_comm == ""
        # the adopted mesh is a factorization of the SAME devices
        assert pexe.mesh.num_devices == 4

    def test_kill_switch_reverts_to_user_config(self):
        feeds = _feeds(1)
        loss = _fresh_mlp()
        pexe = ParallelExecutor(
            loss_name=loss.name,
            build_strategy=BuildStrategy(auto_parallel=True),
            mesh=DeviceMesh(jax.devices()[:4], {"dp": 4}))
        pt.Executor().run(pt.default_startup_program())
        pexe.run(feed=feeds[0], fetch_list=[loss])
        adopted = pexe.build_strategy
        assert pexe.auto_plan_report() is not None
        old = _flags.get_flag("auto_parallel")
        try:
            _flags.set_flag("auto_parallel", False)
            pexe.run(feed=feeds[0], fetch_list=[loss])
            # reverted: the user's own strategy/mesh are live again
            assert dict(pexe.mesh.axes) == {"dp": 4}
            assert pexe.build_strategy is not adopted
            assert pexe.build_strategy.reduce_strategy == \
                ReduceStrategy.AllReduce
        finally:
            _flags.set_flag("auto_parallel", old)

    def test_kill_switch_is_in_compile_cache_key(self):
        from paddle_tpu.framework.executor import _fusion_flags_key
        old = _flags.get_flag("auto_parallel")
        try:
            _flags.set_flag("auto_parallel", True)
            on = _fusion_flags_key()
            _flags.set_flag("auto_parallel", False)
            off = _fusion_flags_key()
            assert on != off
        finally:
            _flags.set_flag("auto_parallel", old)

    def test_plain_executor_without_auto_is_untouched(self):
        loss = _fresh_mlp()
        pexe = ParallelExecutor(
            loss_name=loss.name, build_strategy=BuildStrategy(),
            mesh=DeviceMesh(jax.devices()[:4], {"dp": 4}))
        pt.Executor().run(pt.default_startup_program())
        pexe.run(feed=_feeds(1)[0], fetch_list=[loss])
        assert pexe.auto_plan_report() is None
        assert dict(pexe.mesh.axes) == {"dp": 4}


# ---------------------------------------------------------------------------
# 5. re-plan on elastic resize (ISSUE property c)
# ---------------------------------------------------------------------------


def _elastic_world(dp, auto=False):
    pt.reset_default_programs()
    pt.reset_global_scope()
    with pt.core.unique_name.guard():
        x = layers.data("x", shape=[16])
        label = layers.data("label", shape=[1], dtype="int64")
        h = layers.fc(x, size=32, act="relu")
        loss = layers.mean(layers.softmax_with_cross_entropy(
            layers.fc(h, size=4), label))
        pt.optimizer.MomentumOptimizer(0.1, momentum=0.9).minimize(loss)
    bst = BuildStrategy(auto_parallel=auto)
    bst.reduce_strategy = ReduceStrategy.ReduceScatter
    pexe = ParallelExecutor(loss_name=loss.name, build_strategy=bst,
                            mesh=DeviceMesh(jax.devices()[:dp],
                                            {"dp": dp}))
    pt.Executor().run(pt.default_startup_program())
    return loss, pexe


def _elastic_feeds(n, batch=8):
    rng = np.random.RandomState(0)
    return [{"x": rng.rand(batch, 16).astype("float32"),
             "label": rng.randint(0, 4, (batch, 1)).astype("int64")}
            for _ in range(n)]


class TestReplanOnResize:
    def _save_dp2(self, root, feeds):
        loss, pexe = _elastic_world(2)
        ref = []
        for i, f in enumerate(feeds):
            ref.append(float(pexe.run(feed=f, fetch_list=[loss])[0]))
            if i == 2:
                elastic.save_train_state(root, executor=pexe, step=3)
        return ref

    def test_replan_prices_and_keeps_parity(self, tmp_path):
        feeds = _elastic_feeds(6)
        ref = self._save_dp2(str(tmp_path), feeds)

        loss, kept4 = _elastic_world(4)
        elastic.restore_train_state(str(tmp_path), executor=kept4)
        kept = [float(kept4.run(feed=f, fetch_list=[loss])[0])
                for f in feeds[3:]]

        loss, auto4 = _elastic_world(4, auto=True)
        meta = elastic.restore_train_state(str(tmp_path), executor=auto4)
        rp = meta["replan"]
        assert set(rp) >= {"replanned", "kept", "chosen",
                           "gain_s_per_step"}
        # both restore layouts are PRICED: predicted step seconds and
        # the redistribution wire bytes of each side
        assert rp["kept"]["predicted_step_s"] > 0
        assert rp["kept"]["reshard_wire_bytes"] is not None
        assert rp["chosen"]["predicted_step_s"] > 0
        if rp["replanned"]:
            assert rp["chosen"]["reshard_wire_bytes"] is not None
            assert rp["gain_s_per_step"] > 0
        got = [float(auto4.run(feed=f, fetch_list=[loss])[0])
               for f in feeds[3:]]
        assert max(abs(a - b) for a, b in zip(kept, got)) <= 1e-5
        assert max(abs(a - b) for a, b in zip(ref[3:], got)) <= 1e-5

    def test_replan_is_deterministic(self, tmp_path):
        feeds = _elastic_feeds(4)
        self._save_dp2(str(tmp_path), feeds)
        choices = []
        for _ in range(2):
            loss, auto4 = _elastic_world(4, auto=True)
            meta = elastic.restore_train_state(str(tmp_path),
                                               executor=auto4)
            choices.append((meta["replan"]["chosen"]["point"],
                            tuple(sorted(dict(auto4.mesh.axes).items()))))
        assert choices[0] == choices[1]

    def test_replan_false_suppresses_the_resize_replan(self, tmp_path):
        """replan=False: no resize re-plan record/pricing. (The
        executor's OWN prepare-time planning still runs for an
        auto_parallel strategy — it is what the flag asks for — but the
        elastic decision record must be absent and the restore must
        still land at parity.)"""
        feeds = _elastic_feeds(6)
        ref = self._save_dp2(str(tmp_path), feeds)
        loss, auto4 = _elastic_world(4, auto=True)
        meta = elastic.restore_train_state(str(tmp_path), executor=auto4,
                                           replan=False)
        assert "replan" not in meta
        got = [float(auto4.run(feed=f, fetch_list=[loss])[0])
               for f in feeds[3:]]
        assert max(abs(a - b) for a, b in zip(ref[3:], got)) <= 1e-5

    def test_restore_decision_pins_later_prepares(self, tmp_path):
        """The restore-time decision was priced against the one-time
        reshard cost at a batch the restore could not know; a later
        prepare with the REAL feed batch must honor it instead of
        re-planning batch-keyed and silently overriding it."""
        feeds = _elastic_feeds(4)
        self._save_dp2(str(tmp_path), feeds)
        loss, auto4 = _elastic_world(4, auto=True)
        elastic.restore_train_state(str(tmp_path), executor=auto4)
        decided = auto4.build_strategy
        decided_axes = dict(auto4.mesh.axes)
        # a different batch size than the restore's nominal default
        rng = np.random.RandomState(1)
        big = {"x": rng.rand(16, 16).astype("float32"),
               "label": rng.randint(0, 4, (16, 1)).astype("int64")}
        auto4.run(feed=big, fetch_list=[loss])
        assert auto4.build_strategy is decided
        assert dict(auto4.mesh.axes) == decided_axes

    def test_same_world_restore_never_replans(self, tmp_path):
        feeds = _elastic_feeds(4)
        self._save_dp2(str(tmp_path), feeds)
        loss, auto2 = _elastic_world(2, auto=True)
        meta = elastic.restore_train_state(str(tmp_path), executor=auto2)
        assert "replan" not in meta


# ---------------------------------------------------------------------------
# the committed artifact (ISSUE properties b + acceptance)
# ---------------------------------------------------------------------------


class TestBenchPlanArtifact:
    @pytest.fixture(scope="class")
    def artifact(self):
        path = os.path.join(REPO, "BENCH_PLAN_r19.json")
        if not os.path.exists(path):
            pytest.skip("BENCH_PLAN_r19.json not committed yet")
        with open(path) as f:
            return json.load(f)

    def test_artifact_is_green(self, artifact):
        assert artifact["ok"], [
            (c["model"], c["devices"],
             [ch["name"] for ch in c["checks"] if not ch["ok"]])
            for c in artifact["cells"] if not c["ok"]]

    def test_planner_matches_or_beats_on_at_least_three_cells(self,
                                                              artifact):
        good = [c for c in artifact["cells"]
                if any(ch["name"] == "planner_matches_or_beats"
                       and ch["ok"] for ch in c["checks"])]
        assert len(good) >= 3, [(c["model"], c["devices"])
                                for c in artifact["cells"]]

    def test_wire_bytes_exact_on_every_executed_choice(self, artifact):
        for c in artifact["cells"]:
            ch = next(x for x in c["checks"]
                      if x["name"] == "wire_bytes_exact_on_choice")
            assert ch["ok"] and ch["predicted"] == ch["measured"], (
                c["model"], c["devices"], ch)

    def test_never_predicts_better_but_measures_worse_beyond_band(
            self, artifact):
        for c in artifact["cells"]:
            ch = next(x for x in c["checks"]
                      if x["name"] == "predict_measure_consistent")
            assert ch["ok"] and not ch["violations"], (
                c["model"], c["devices"], ch)


# ---------------------------------------------------------------------------
# lint_program --strategy CLI (the named-reasons surface)
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestLintStrategyCLI:
    def _lint(self, strategy_json):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "lint_program.py"),
             "--model", "mnist", "--json", "--strategy", strategy_json],
            capture_output=True, text=True, env=env)

    def test_feasible_strategy_lints_clean(self):
        p = self._lint('{"dp": 2, "reduce": "reduce_scatter"}')
        assert p.returncode == 0, p.stdout[-2000:] + p.stderr[-2000:]
        rep = json.loads(p.stdout)[0]
        assert rep["strategy_feasible"]["ok"]
        assert rep["errors"] == 0

    def test_infeasible_strategy_exits_2_with_named_reason(self):
        p = self._lint('{"dp": 2, "tp": 2, "reduce": "reduce_scatter"}')
        assert p.returncode == 2, p.stdout[-2000:] + p.stderr[-2000:]
        rep = json.loads(p.stdout)[0]
        codes = [r["code"] for r in rep["strategy_feasible"]["reasons"]]
        assert codes == ["tp-unannotated"]
        assert rep["gate_rejected"]
