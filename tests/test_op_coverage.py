"""Registry-walking per-op checks: forward vs numpy + grad vs finite diff.

≙ the reference's per-op test corpus (~230 test_*_op.py files over
python/paddle/fluid/tests/unittests/, all built on op_test.py): here ONE
parametrized walker covers the registry, driven by a spec table. Every
registered op must be in SPECS (directly checked here), COVERED_ELSEWHERE
(named dedicated test), or EXCLUDED (with a reason) — enforced by
test_registry_fully_accounted, so newly-registered ops fail CI until they
get a check.

Spec keys:
  ins        callable(rng) -> {slot: np array | [np arrays]}
  attrs      dict (or callable(rng) -> dict)
  ref        callable(ins, attrs) -> {slot: expected np} — forward parity
             (ins values are normalized to lists). Omit for smoke-only ops
             (outputs asserted finite/shaped but not value-checked).
  grad       [slot, ...] — analytic-vs-finite-difference gradient check
  out_slot   output slot the grad check reduces over (default "Out")
  is_test    run the lowering in inference mode
  atol/rtol  forward tolerances (default 1e-5)
"""

from __future__ import annotations

import numpy as np
import pytest

from op_test import check_grad, check_output, run_op

# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _np(ins):
    """Normalize a spec's ins dict to {slot: [np arrays]}."""
    return {k: [np.asarray(x) for x in (v if isinstance(v, list) else [v])]
            for k, v in ins.items()}


def _away(rng, shape, lo=0.2, hi=2.0):
    """Floats with |x| in [lo, hi] — away from kinks at 0."""
    mag = rng.uniform(lo, hi, shape)
    sign = np.where(rng.rand(*shape) < 0.5, -1.0, 1.0)
    return (mag * sign).astype("float32")


def _pos(rng, shape, lo=0.2, hi=2.0):
    return rng.uniform(lo, hi, shape).astype("float32")


def _unary(np_ref, make_x=None, grad=True, attrs=None, **kw):
    make_x = make_x or (lambda r: _away(r, (4, 6)))
    spec = dict(ins=lambda r: {"X": make_x(r)},
                attrs=dict(attrs or {}),
                grad=["X"] if grad else [])
    if np_ref is not None:
        spec["ref"] = lambda i, a: {"Out": np_ref(i["X"][0])}
    spec.update(kw)
    return spec


def _binary(np_ref, make_x=None, make_y=None, grad=("X", "Y"), attrs=None,
            **kw):
    make_x = make_x or (lambda r: _away(r, (4, 6)))
    make_y = make_y or (lambda r: _away(r, (4, 6)))
    spec = dict(ins=lambda r: {"X": make_x(r), "Y": make_y(r)},
                attrs=dict(attrs or {}),
                grad=list(grad))
    if np_ref is not None:
        spec["ref"] = lambda i, a: {"Out": np_ref(i["X"][0], i["Y"][0])}
    spec.update(kw)
    return spec


def _ints(rng, shape, hi=5):
    return rng.randint(0, hi, shape).astype("int64")


def _spp_ref(x, levels):
    """Spatial pyramid max-pool: level l = 2^l x 2^l grid of max bins,
    blocks concatenated level-major, h-bin then w-bin within a level."""
    outs = []
    n, c, h, w = x.shape
    for lvl in range(levels):
        bins = 2 ** lvl
        for bi in range(bins):
            h0, h1 = h * bi // bins, -(-h * (bi + 1) // bins)
            for bj in range(bins):
                w0, w1 = w * bj // bins, -(-w * (bj + 1) // bins)
                outs.append(x[:, :, h0:h1, w0:w1].max(axis=(2, 3)))
    return np.concatenate(outs, axis=1)


def _softmax_np(x, axis=-1):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


def _sigmoid_np(x):
    return 1.0 / (1.0 + np.exp(-x))


# ---------------------------------------------------------------------------
# spec table
# ---------------------------------------------------------------------------

SPECS = {}

# -- unary activations / math ----------------------------------------------
SPECS.update({
    "abs": _unary(np.abs),
    "ceil": _unary(np.ceil, grad=False),
    "floor": _unary(np.floor, grad=False),
    "round": _unary(np.round, grad=False),
    "cos": _unary(np.cos),
    "sin": _unary(np.sin),
    "exp": _unary(np.exp),
    "log": _unary(np.log, make_x=lambda r: _pos(r, (4, 6))),
    "sqrt": _unary(np.sqrt, make_x=lambda r: _pos(r, (4, 6))),
    "rsqrt": _unary(lambda x: 1 / np.sqrt(x),
                    make_x=lambda r: _pos(r, (4, 6))),
    "reciprocal": _unary(lambda x: 1 / x),
    "square": _unary(np.square),
    "sigmoid": _unary(_sigmoid_np),
    "logsigmoid": _unary(lambda x: np.log(_sigmoid_np(x))),
    "tanh": _unary(np.tanh),
    "tanh_shrink": _unary(lambda x: x - np.tanh(x)),
    "softplus": _unary(lambda x: np.log1p(np.exp(x))),
    "softsign": _unary(lambda x: x / (1 + np.abs(x))),
    "sign": _unary(np.sign, grad=False),
    "silu": _unary(lambda x: x * _sigmoid_np(x)),
    "swish": _unary(lambda x: x * _sigmoid_np(x)),
    "gelu": _unary(  # jax.nn.gelu default is the tanh approximation
        lambda x: 0.5 * x * (1 + np.tanh(
            np.sqrt(2 / np.pi) * (x + 0.044715 * x ** 3))),
        atol=1e-4),
    "relu": _unary(lambda x: np.maximum(x, 0)),
    "relu6": _unary(lambda x: np.clip(x, 0, 6)),
    "elu": _unary(lambda x: np.where(x > 0, x, np.exp(x) - 1),
                  attrs={"alpha": 1.0}),
    "leaky_relu": _unary(lambda x: np.where(x > 0, x, 0.02 * x),
                         attrs={"alpha": 0.02}),
    "brelu": _unary(lambda x: np.clip(x, -1.0, 1.0),
                    attrs={"t_min": -1.0, "t_max": 1.0},
                    make_x=lambda r: _away(r, (4, 6), 0.2, 2.0) * 0.9),
    "hard_shrink": _unary(
        lambda x: np.where(np.abs(x) > 0.5, x, 0.0),
        attrs={"threshold": 0.5},
        make_x=lambda r: _away(r, (4, 6), 0.6, 2.0)),
    "hard_sigmoid": _unary(
        lambda x: np.clip(0.2 * x + 0.5, 0.0, 1.0),
        attrs={"slope": 0.2, "offset": 0.5},
        make_x=lambda r: _away(r, (4, 6), 0.2, 2.0)),
    "soft_shrink": _unary(
        lambda x: np.where(x > 0.5, x - 0.5, np.where(x < -0.5, x + 0.5, 0)),
        attrs={"lambda": 0.5},
        make_x=lambda r: _away(r, (4, 6), 0.6, 2.0)),
    "thresholded_relu": _unary(
        lambda x: np.where(x > 0.5, x, 0.0), attrs={"threshold": 0.5},
        make_x=lambda r: _away(r, (4, 6), 0.6, 2.0)),
    "pow": _unary(lambda x: np.power(x, 2.0), attrs={"factor": 2.0},
                  make_x=lambda r: _pos(r, (4, 6))),
    "scale": _unary(lambda x: 3.0 * x + 1.0,
                    attrs={"scale": 3.0, "bias": 1.0,
                           "bias_after_scale": True}),
    "clip": _unary(lambda x: np.clip(x, -1.0, 1.0),
                   attrs={"min": -1.0, "max": 1.0},
                   make_x=lambda r: _away(r, (4, 6), 0.2, 0.9)),
    "isfinite": _unary(lambda x: np.array(np.isfinite(x).all()),
                       grad=False),
    "logical_not": dict(
        ins=lambda r: {"X": r.rand(4, 6) > 0.5},
        ref=lambda i, a: {"Out": ~i["X"][0]}, grad=[]),
    "prelu": dict(
        ins=lambda r: {"X": _away(r, (4, 6)),
                       "Alpha": _pos(r, (1,), 0.1, 0.5)},
        attrs={"mode": "all"},
        ref=lambda i, a: {"Out": np.where(i["X"][0] > 0, i["X"][0],
                                          i["Alpha"][0] * i["X"][0])},
        grad=["X", "Alpha"]),
    "clip_by_norm": _unary(
        lambda x: x * (1.0 / max(1.0, np.linalg.norm(x) / 1.0)),
        attrs={"max_norm": 1.0}, grad=True),
})

# -- binary elementwise ------------------------------------------------------
SPECS.update({
    "elementwise_add": _binary(np.add),
    "elementwise_sub": _binary(np.subtract),
    "elementwise_mul": _binary(np.multiply),
    "elementwise_div": _binary(np.divide),
    "elementwise_max": _binary(np.maximum),
    "elementwise_min": _binary(np.minimum),
    "elementwise_pow": _binary(np.power,
                               make_x=lambda r: _pos(r, (4, 6)),
                               make_y=lambda r: _pos(r, (4, 6), 0.5, 1.5)),
    "elementwise_mod": _binary(np.mod,
                               make_x=lambda r: _ints(r, (4, 6), 20),
                               make_y=lambda r: _ints(r, (4, 6), 5) + 1,
                               grad=()),
    "elementwise_floordiv": _binary(np.floor_divide,
                                    make_x=lambda r: _ints(r, (4, 6), 20),
                                    make_y=lambda r: _ints(r, (4, 6), 5) + 1,
                                    grad=()),
    "equal": _binary(np.equal, make_x=lambda r: _ints(r, (4, 6)),
                     make_y=lambda r: _ints(r, (4, 6)), grad=()),
    "not_equal": _binary(np.not_equal, make_x=lambda r: _ints(r, (4, 6)),
                         make_y=lambda r: _ints(r, (4, 6)), grad=()),
    "less_than": _binary(np.less, make_x=lambda r: _ints(r, (4, 6)),
                         make_y=lambda r: _ints(r, (4, 6)), grad=()),
    "less_equal": _binary(np.less_equal, make_x=lambda r: _ints(r, (4, 6)),
                          make_y=lambda r: _ints(r, (4, 6)), grad=()),
    "greater_than": _binary(np.greater, make_x=lambda r: _ints(r, (4, 6)),
                            make_y=lambda r: _ints(r, (4, 6)), grad=()),
    "greater_equal": _binary(np.greater_equal,
                             make_x=lambda r: _ints(r, (4, 6)),
                             make_y=lambda r: _ints(r, (4, 6)), grad=()),
    "logical_and": _binary(np.logical_and,
                           make_x=lambda r: r.rand(4, 6) > 0.5,
                           make_y=lambda r: r.rand(4, 6) > 0.5, grad=()),
    "logical_or": _binary(np.logical_or,
                          make_x=lambda r: r.rand(4, 6) > 0.5,
                          make_y=lambda r: r.rand(4, 6) > 0.5, grad=()),
    "logical_xor": _binary(np.logical_xor,
                           make_x=lambda r: r.rand(4, 6) > 0.5,
                           make_y=lambda r: r.rand(4, 6) > 0.5, grad=()),
})

# -- reductions / sorts ------------------------------------------------------
SPECS.update({
    "reduce_sum": _unary(lambda x: x.sum(axis=1), attrs={"dim": [1]}),
    "reduce_mean": _unary(lambda x: x.mean(axis=1), attrs={"dim": [1]}),
    "reduce_max": _unary(lambda x: x.max(axis=1), attrs={"dim": [1]}),
    "reduce_min": _unary(lambda x: x.min(axis=1), attrs={"dim": [1]}),
    "reduce_prod": _unary(lambda x: x.prod(axis=1), attrs={"dim": [1]}),
    "mean": _unary(lambda x: np.array(x.mean(), dtype=np.float32)),
    "sum": dict(
        ins=lambda r: {"X": [_away(r, (4, 6)), _away(r, (4, 6)),
                             _away(r, (4, 6))]},
        ref=lambda i, a: {"Out": i["X"][0] + i["X"][1] + i["X"][2]},
        grad=["X"]),
    "cumsum": _unary(lambda x: np.cumsum(x, axis=1), attrs={"axis": 1}),
    "squared_l2_norm": _unary(
        lambda x: np.array((x ** 2).sum(), dtype=np.float32)),
    "squared_l2_distance": _binary(
        lambda x, y: ((x - y) ** 2).sum(axis=1, keepdims=True)),
    "cos_sim": _binary(
        lambda x, y: (x * y).sum(1, keepdims=True) /
        (np.linalg.norm(x, axis=1, keepdims=True) *
         np.linalg.norm(y, axis=1, keepdims=True))),
    "norm": _unary(None, grad=True, attrs={"axis": 1}),
    "arg_max": _unary(lambda x: x.argmax(axis=1), attrs={"axis": 1},
                      grad=False),
    "arg_min": _unary(lambda x: x.argmin(axis=1), attrs={"axis": 1},
                      grad=False),
    "argsort": _unary(lambda x: np.sort(x, axis=1), attrs={"axis": 1},
                      grad=False),
    "top_k": dict(
        ins=lambda r: {"X": r.rand(4, 8).astype("float32")},
        attrs={"k": 3},
        ref=lambda i, a: {"Out": -np.sort(-i["X"][0], axis=1)[:, :3]},
        grad=[]),
    "shape": dict(
        ins=lambda r: {"Input": _away(r, (4, 6))},
        ref=lambda i, a: {"Out": np.array([4, 6], dtype=np.int64)},
        grad=[]),
    "is_empty": _unary(lambda x: np.array(x.size == 0), grad=False),
})

# -- tensor manipulation -----------------------------------------------------
SPECS.update({
    "cast": _unary(lambda x: x.astype("float64"),
                   attrs={"out_dtype": "float64"}, grad=False),
    "concat": dict(
        ins=lambda r: {"X": [_away(r, (4, 3)), _away(r, (4, 5))]},
        attrs={"axis": 1},
        ref=lambda i, a: {"Out": np.concatenate(i["X"], axis=1)},
        grad=["X"]),
    "split": dict(
        ins=lambda r: {"X": _away(r, (4, 6))},
        attrs={"num": 2, "axis": 1},
        ref=lambda i, a: {"Out": [i["X"][0][:, :3], i["X"][0][:, 3:]]},
        grad=[]),
    "reshape": _unary(lambda x: x.reshape(2, 12), attrs={"shape": [2, 12]}),
    "flatten": _unary(lambda x: x.reshape(4, -1), attrs={"axis": 1},
                      make_x=lambda r: _away(r, (4, 2, 3))),
    "squeeze": _unary(lambda x: x.squeeze(1), attrs={"axes": [1]},
                      make_x=lambda r: _away(r, (4, 1, 6))),
    "unsqueeze": _unary(lambda x: x[:, None, :], attrs={"axes": [1]}),
    "transpose": _unary(lambda x: x.T, attrs={"axis": [1, 0]}),
    "stack": dict(
        ins=lambda r: {"X": [_away(r, (4, 3)), _away(r, (4, 3))]},
        attrs={"axis": 0},
        ref=lambda i, a: {"Y": np.stack(i["X"], axis=0)},
        grad=["X"], out_slot="Y"),
    "unstack": dict(
        ins=lambda r: {"X": _away(r, (3, 4))},
        attrs={"axis": 0},
        ref=lambda i, a: {"Y": [i["X"][0][j] for j in range(3)]},
        grad=[]),
    "slice": _unary(lambda x: x[1:3, :], attrs={"axes": [0], "starts": [1],
                                                "ends": [3]}),
    "crop": _unary(lambda x: x[1:3, 2:5],
                   attrs={"offsets": [1, 2], "shape": [2, 3]}),
    "pad": _unary(lambda x: np.pad(x, ((1, 2), (0, 1))),
                  attrs={"paddings": [1, 2, 0, 1], "pad_value": 0.0}),
    "pad_constant_like": dict(
        ins=lambda r: {"X": _away(r, (5, 7)), "Y": _away(r, (4, 6))},
        attrs={"pad_value": 0.0},
        ref=lambda i, a: {"Out": np.pad(i["Y"][0], ((0, 1), (0, 1)))},
        grad=["Y"]),
    "expand": _unary(lambda x: np.tile(x, (2, 3)),
                     attrs={"expand_times": [2, 3]}),
    "expand_as": dict(
        ins=lambda r: {"X": _away(r, (4, 1)), "Y": _away(r, (4, 6))},
        ref=lambda i, a: {"Out": np.tile(i["X"][0], (1, 6))},
        grad=["X"]),
    "gather": dict(
        ins=lambda r: {"X": _away(r, (6, 3)),
                       "Index": np.array([0, 2, 5], dtype="int64")},
        ref=lambda i, a: {"Out": i["X"][0][[0, 2, 5]]},
        grad=["X"]),
    "scatter": dict(
        ins=lambda r: {"X": _away(r, (6, 3)),
                       "Ids": np.array([1, 4], dtype="int64"),
                       "Updates": _away(r, (2, 3))},
        ref=lambda i, a: {"Out": _scatter_ref(i)},
        grad=["Updates"]),
    "reverse": _unary(lambda x: x[:, ::-1], attrs={"axis": [1]}),
    "multiplex": dict(
        ins=lambda r: {"Ids": np.array([[0], [1], [0]], dtype="int64"),
                       "X": [_away(r, (3, 4)), _away(r, (3, 4))]},
        ref=lambda i, a: {"Out": np.stack([i["X"][0][0], i["X"][1][1],
                                           i["X"][0][2]])},
        grad=[]),
    "one_hot": dict(
        ins=lambda r: {"X": np.array([[1], [0], [3]], dtype="int64")},
        attrs={"depth": 4},
        ref=lambda i, a: {"Out": np.eye(4, dtype="float32")[
            i["X"][0].reshape(-1)]},
        grad=[]),
    "label_smooth": dict(
        ins=lambda r: {"X": np.eye(4, dtype="float32")[
            r.randint(0, 4, (5,))]},
        attrs={"epsilon": 0.1},
        ref=lambda i, a: {"Out": i["X"][0] * 0.9 + 0.1 / 4},
        grad=["X"]),
    "fill_constant": dict(
        ins=lambda r: {},
        attrs={"shape": [2, 3], "value": 2.5, "dtype": "float32"},
        ref=lambda i, a: {"Out": np.full((2, 3), 2.5, dtype="float32")},
        grad=[]),
    "fill_constant_batch_size_like": dict(
        ins=lambda r: {"Input": _away(r, (5, 2))},
        attrs={"shape": [1, 3], "value": 1.5, "dtype": "float32"},
        ref=lambda i, a: {"Out": np.full((5, 3), 1.5, dtype="float32")},
        grad=[]),
    "fill_zeros_like": _unary(np.zeros_like, grad=False),
    "assign": _unary(lambda x: x, grad=True),
    "assign_value": dict(
        ins=lambda r: {},
        attrs={"shape": [2, 2], "values": [1.0, 2.0, 3.0, 4.0],
               "dtype": "float32"},
        ref=lambda i, a: {"Out": np.array([[1, 2], [3, 4]],
                                          dtype="float32")},
        grad=[]),
    "increment": _unary(lambda x: x + 1.0, attrs={"step": 1.0},
                        make_x=lambda r: np.array([3.0], dtype="float32"),
                        grad=False),
    "arange": dict(
        ins=lambda r: {},
        attrs={"start": 1, "end": 7, "step": 2, "dtype": "int64"},
        ref=lambda i, a: {"Out": np.arange(1, 7, 2, dtype="int64")},
        grad=[]),
    "where": dict(
        ins=lambda r: {"Condition": r.rand(4, 6) > 0.5,
                       "X": _away(r, (4, 6)), "Y": _away(r, (4, 6))},
        ref=lambda i, a: {"Out": np.where(i["Condition"][0], i["X"][0],
                                          i["Y"][0])},
        grad=["X", "Y"]),
    "lookup_table": dict(
        ins=lambda r: {"W": _away(r, (8, 4)),
                       "Ids": np.array([[1], [3], [7]], dtype="int64")},
        ref=lambda i, a: {"Out": i["W"][0][[1, 3, 7]]},
        grad=["W"]),
    "lookup_sparse_table": dict(
        ins=lambda r: {"W": _away(r, (8, 4)),
                       "Ids": np.array([1, 3, -1, 7], dtype="int64")},
        # padded (-1) ids yield zero rows (≙ the auto-grown init value)
        ref=lambda i, a: {"Out": np.concatenate([
            i["W"][0][[1, 3]], np.zeros((1, 4), "float32"),
            i["W"][0][[7]]])},
        grad=[]),
    "cache_write": dict(
        ins=lambda r: {"Cache": _away(r, (2, 3, 6, 4)),
                       "New": _away(r, (2, 3, 1, 4)),
                       "Pos": np.array([[2.0]], "float32")},
        attrs={"axis": 2},
        ref=lambda i, a: {"Out": _cache_write_ref(
            i["Cache"][0], i["New"][0], 2, 2)},
        grad=[]),
    "split_ids": dict(
        ins=lambda r: {"Ids": np.array([0, 3, 5, 6, 9], dtype="int64")},
        attrs={"num_shards": 2},
        # modulo routing, order-preserving, -1 padded (≙ split_ids_op.h)
        ref=lambda i, a: {
            "Out": [np.array([0, 6, -1, -1, -1], "int32"),
                    np.array([3, 5, 9, -1, -1], "int32")],
            "Count": np.array([2, 3], "int32")},
        grad=[]),
    "merge_ids": dict(
        # inverse of split_ids: Ids = the ORIGINAL query, X = per-shard
        # padded id tensors, Rows = per-shard looked-up row values; Out
        # restores original order (≙ merge_ids_op.h)
        ins=lambda r: {"Ids": np.array([0, 3, 5, 6, 9], "int64"),
                       "X": [np.array([0, 6, -1, -1, -1], "int64"),
                             np.array([3, 5, 9, -1, -1], "int64")],
                       "Rows": [np.arange(15, dtype="float32"
                                          ).reshape(5, 3),
                                np.arange(100, 115, dtype="float32"
                                          ).reshape(5, 3)]},
        ref=lambda i, a: {"Out": np.stack([
            i["Rows"][0][0],       # id 0 -> shard0 row 0
            i["Rows"][1][0],       # id 3 -> shard1 row 0
            i["Rows"][1][1],       # id 5 -> shard1 row 1
            i["Rows"][0][1],       # id 6 -> shard0 row 1
            i["Rows"][1][2]])},    # id 9 -> shard1 row 2
        grad=[]),
})


def _scatter_ref(i):
    out = i["X"][0].copy()
    out[[1, 4]] = i["Updates"][0]
    return out


# -- nn ----------------------------------------------------------------------

def _conv2d_ref(x, w, stride=1, pad=0):
    n, c, h, ww = x.shape
    o, _, kh, kw = w.shape
    xp = np.pad(x, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    oh = (h + 2 * pad - kh) // stride + 1
    ow = (ww + 2 * pad - kw) // stride + 1
    out = np.zeros((n, o, oh, ow), dtype=np.float32)
    for i in range(oh):
        for j in range(ow):
            patch = xp[:, :, i * stride:i * stride + kh,
                       j * stride:j * stride + kw]
            out[:, :, i, j] = np.einsum("ncij,ocij->no", patch, w)
    return out


def _pool2d_ref(x, ksize, stride, ptype):
    n, c, h, w = x.shape
    oh = (h - ksize) // stride + 1
    ow = (w - ksize) // stride + 1
    out = np.zeros((n, c, oh, ow), dtype=np.float32)
    for i in range(oh):
        for j in range(ow):
            patch = x[:, :, i * stride:i * stride + ksize,
                      j * stride:j * stride + ksize]
            out[:, :, i, j] = (patch.max((2, 3)) if ptype == "max"
                               else patch.mean((2, 3)))
    return out


def _bn_train_ref(i, a):
    x, scale, bias = i["X"][0], i["Scale"][0], i["Bias"][0]
    mean = x.mean((0, 2, 3))
    var = x.var((0, 2, 3))
    y = ((x - mean[None, :, None, None]) /
         np.sqrt(var[None, :, None, None] + 1e-5) *
         scale[None, :, None, None] + bias[None, :, None, None])
    return {"Y": y}


def _layer_norm_ref(i, a):
    x, scale, bias = i["X"][0], i["Scale"][0], i["Bias"][0]
    mean = x.mean(1, keepdims=True)
    var = x.var(1, keepdims=True)
    return {"Y": (x - mean) / np.sqrt(var + 1e-5) * scale + bias}


SPECS.update({
    "conv2d": dict(
        ins=lambda r: {"Input": _away(r, (2, 3, 5, 5)),
                       "Filter": _away(r, (4, 3, 3, 3)) * 0.3},
        attrs={"strides": [1, 1], "paddings": [1, 1]},
        ref=lambda i, a: {"Output": _conv2d_ref(i["Input"][0],
                                                i["Filter"][0], 1, 1)},
        # grad tol: the central-difference reference itself carries ~1e-2
        # relative noise on this jaxlib's f32 conv emitter (spatially
        # symmetric analytic entries come back asymmetric from the
        # NUMERIC side) — widen just past it, value assertion retained
        grad=["Input", "Filter"], out_slot="Output", atol=1e-4,
        grad_atol=2e-2, grad_rtol=2e-2),
    "depthwise_conv2d": dict(
        ins=lambda r: {"Input": _away(r, (2, 3, 5, 5)),
                       "Filter": _away(r, (3, 1, 3, 3)) * 0.3},
        attrs={"strides": [1, 1], "paddings": [1, 1], "groups": 3},
        grad=["Input", "Filter"], out_slot="Output"),
    "conv2d_transpose": dict(
        ins=lambda r: {"Input": _away(r, (2, 3, 4, 4)),
                       "Filter": _away(r, (3, 2, 3, 3)) * 0.3},
        attrs={"strides": [2, 2], "paddings": [0, 0]},
        grad=["Input", "Filter"], out_slot="Output"),
    "conv3d": dict(
        ins=lambda r: {"Input": _away(r, (1, 2, 4, 4, 4)),
                       "Filter": _away(r, (3, 2, 2, 2, 2)) * 0.3},
        attrs={"strides": [1, 1, 1], "paddings": [0, 0, 0]},
        grad=["Input", "Filter"], out_slot="Output"),
    "pool2d": dict(
        ins=lambda r: {"X": r.rand(2, 3, 6, 6).astype("float32")},
        attrs={"pooling_type": "avg", "ksize": [2, 2], "strides": [2, 2],
               "paddings": [0, 0]},
        ref=lambda i, a: {"Out": _pool2d_ref(i["X"][0], 2, 2, "avg")},
        grad=["X"]),
    "batch_norm": dict(
        ins=lambda r: {"X": _away(r, (3, 4, 5, 5)),
                       "Scale": _pos(r, (4,)), "Bias": _away(r, (4,)),
                       "Mean": np.zeros(4, "float32"),
                       "Variance": np.ones(4, "float32")},
        attrs={"epsilon": 1e-5, "momentum": 0.9},
        ref=_bn_train_ref, grad=["X", "Scale", "Bias"], out_slot="Y",
        # both sum(y) and sum(y^2) of a batch-normalized output are invariant
        # in x by construction (sum(x_hat)=0, sum(x_hat^2)=N per channel), so
        # those reductions compare pure noise; a fixed-weight reduction
        # exposes the real Jacobian
        reduce="weighted", atol=1e-3),
    "layer_norm": dict(
        ins=lambda r: {"X": _away(r, (4, 6)),
                       "Scale": _pos(r, (6,)), "Bias": _away(r, (6,))},
        attrs={"epsilon": 1e-5, "begin_norm_axis": 1},
        ref=_layer_norm_ref, grad=["X", "Scale", "Bias"], out_slot="Y",
        reduce="weighted", atol=1e-3),
    "softmax": _unary(_softmax_np),
    "log_softmax": _unary(lambda x: np.log(_softmax_np(x))),
    "l2_normalize": _unary(
        lambda x: x / np.sqrt((x ** 2).sum(1, keepdims=True) + 1e-10),
        attrs={"axis": 1}),
    "lrn": dict(
        ins=lambda r: {"X": _away(r, (2, 5, 4, 4))},
        attrs={"n": 3}, grad=["X"]),
    "maxout": dict(
        ins=lambda r: {"X": _away(r, (2, 6, 4, 4))},
        attrs={"groups": 3}, grad=["X"]),
    "dropout": _unary(lambda x: x, is_test=True, grad=True,
                      attrs={"dropout_prob": 0.5, "is_test": True,
                             "dropout_implementation": "upscale_in_train"}),
    "grid_sampler": dict(
        ins=lambda r: {"X": _away(r, (2, 3, 4, 4)),
                       "Grid": r.uniform(-0.8, 0.8,
                                         (2, 4, 4, 2)).astype("float32")},
        grad=["X"], out_slot="Output"),
    "bilinear_interp": dict(
        ins=lambda r: {"X": _away(r, (2, 3, 4, 4))},
        attrs={"out_h": 8, "out_w": 8},
        grad=["X"]),
    "im2sequence": dict(
        ins=lambda r: {"X": _away(r, (2, 3, 6, 6))},
        attrs={"kernels": [2, 2], "strides": [2, 2],
               "paddings": [0, 0, 0, 0]},
        # each output row = one 2x2 patch, channel-major, in row-major
        # patch order (≙ im2sequence_op.h Im2ColFunctor layout)
        ref=lambda i, a: {"Out": np.stack([
            i["X"][0][b, :, 2*ph:2*ph+2, 2*pw:2*pw+2].reshape(-1)
            for b in range(2) for ph in range(3) for pw in range(3)])},
        grad=[]),
    "spp": dict(
        ins=lambda r: {"X": _away(r, (2, 3, 4, 4))},
        attrs={"pyramid_height": 2, "pooling_type": "max"},
        ref=lambda i, a: {"Out": _spp_ref(i["X"][0], 2)},
        grad=[]),
    "mul": dict(
        ins=lambda r: {"X": _away(r, (4, 6)), "Y": _away(r, (6, 3))},
        attrs={"x_num_col_dims": 1, "y_num_col_dims": 1},
        ref=lambda i, a: {"Out": i["X"][0] @ i["Y"][0]},
        grad=["X", "Y"]),
    "matmul": dict(
        ins=lambda r: {"X": _away(r, (4, 6)), "Y": _away(r, (6, 3))},
        attrs={"transpose_X": False, "transpose_Y": False},
        ref=lambda i, a: {"Out": i["X"][0] @ i["Y"][0]},
        grad=["X", "Y"]),
    "bilinear_tensor_product": dict(
        ins=lambda r: {"X": _away(r, (3, 4)), "Y": _away(r, (3, 5)),
                       "Weight": _away(r, (2, 4, 5)) * 0.3,
                       "Bias": _away(r, (1, 2))},
        ref=lambda i, a: {"Out": np.einsum(
            "bi,kij,bj->bk", i["X"][0], i["Weight"][0], i["Y"][0])
            + i["Bias"][0]},
        grad=["X", "Y", "Weight"]),
    "row_conv": dict(
        ins=lambda r: {"X": _away(r, (2, 5, 3)),
                       "Filter": _away(r, (3, 3)) * 0.3},
        grad=["X", "Filter"]),
    "fused_attention": dict(
        ins=lambda r: {"Q": _away(r, (1, 2, 4, 8)) * 0.3,
                       "K": _away(r, (1, 2, 4, 8)) * 0.3,
                       "V": _away(r, (1, 2, 4, 8)) * 0.3},
        attrs={"backend": "xla"},
        grad=["Q", "K", "V"]),
})

# -- losses ------------------------------------------------------------------


def _huber_ref(i, a):
    d = a.get("delta", 1.0)
    r = i["Y"][0] - i["X"][0]
    return {"Out": np.where(np.abs(r) <= d, 0.5 * r * r,
                            d * (np.abs(r) - 0.5 * d))}


def _smooth_l1_ref(i, a):
    sigma2 = a.get("sigma", 1.0) ** 2
    d = i["X"][0] - i["Y"][0]
    l = np.where(np.abs(d) < 1.0 / sigma2,
                 0.5 * d * d * sigma2, np.abs(d) - 0.5 / sigma2)
    return {"Out": l.sum(axis=1, keepdims=True)}


SPECS.update({
    "cross_entropy": dict(
        ins=lambda r: {"X": _softmax_np(r.rand(4, 5)).astype("float32"),
                       "Label": _ints(r, (4, 1), 5)},
        ref=lambda i, a: {"Y": -np.log(i["X"][0][
            np.arange(4), i["Label"][0].reshape(-1)]).reshape(4, 1)},
        grad=["X"], out_slot="Y"),
    "softmax_with_cross_entropy": dict(
        ins=lambda r: {"Logits": _away(r, (4, 5)),
                       "Label": _ints(r, (4, 1), 5)},
        ref=lambda i, a: {"Loss": -np.log(_softmax_np(i["Logits"][0])[
            np.arange(4), i["Label"][0].reshape(-1)]).reshape(4, 1)},
        grad=["Logits"], out_slot="Loss"),
    "sigmoid_cross_entropy_with_logits": dict(
        ins=lambda r: {"X": _away(r, (4, 5)),
                       "Label": r.rand(4, 5).astype("float32")},
        ref=lambda i, a: {"Out": np.maximum(i["X"][0], 0)
                          - i["X"][0] * i["Label"][0]
                          + np.log1p(np.exp(-np.abs(i["X"][0])))},
        grad=["X"]),
    "hinge_loss": dict(
        ins=lambda r: {"Logits": _away(r, (4, 1)),
                       "Labels": _ints(r, (4, 1), 2).astype("float32")},
        ref=lambda i, a: {"Loss": np.maximum(
            0.0, 1.0 - (2 * i["Labels"][0] - 1) * i["Logits"][0])},
        grad=["Logits"], out_slot="Loss"),
    "huber_loss": dict(
        ins=lambda r: {"X": _away(r, (4, 1)), "Y": _away(r, (4, 1))},
        attrs={"delta": 1.0}, ref=_huber_ref, grad=["X"], atol=1e-4),
    "log_loss": dict(
        ins=lambda r: {"Predicted": r.uniform(
            0.1, 0.9, (4, 1)).astype("float32"),
            "Labels": _ints(r, (4, 1), 2).astype("float32")},
        attrs={"epsilon": 1e-4},
        grad=["Predicted"], out_slot="Loss"),
    "mse_loss": dict(
        ins=lambda r: {"X": _away(r, (4, 3)), "Y": _away(r, (4, 3))},
        ref=lambda i, a: {"Out": (i["X"][0] - i["Y"][0]) ** 2},
        grad=["X"]),
    "smooth_l1_loss": dict(
        ins=lambda r: {"X": _away(r, (4, 3)), "Y": _away(r, (4, 3))},
        attrs={"sigma": 1.0}, grad=["X"]),
    "rank_loss": dict(
        ins=lambda r: {"Left": _away(r, (4, 1)), "Right": _away(r, (4, 1)),
                       "Label": _ints(r, (4, 1), 2).astype("float32")},
        grad=["Left", "Right"]),
    "margin_rank_loss": dict(
        ins=lambda r: {"X1": _away(r, (4, 1)), "X2": _away(r, (4, 1)),
                       "Label": (2.0 * _ints(r, (4, 1), 2) - 1)
                       .astype("float32")},
        attrs={"margin": 0.1},
        grad=["X1", "X2"]),
    "nce": dict(
        ins=lambda r: {"Input": _away(r, (3, 4)),
                       "Label": _ints(r, (3, 1), 6),
                       "Weight": _away(r, (6, 4)) * 0.3,
                       "Bias": _away(r, (6,)) * 0.1},
        attrs={"num_total_classes": 6, "num_neg_samples": 3},
        grad=["Input", "Weight"], out_slot="Cost"),
    "hierarchical_sigmoid": dict(
        ins=lambda r: {"X": _away(r, (3, 4)),
                       "Label": _ints(r, (3, 1), 6),
                       "W": _away(r, (5, 4)) * 0.3,
                       "Bias": _away(r, (5,)) * 0.1},
        attrs={"num_classes": 6},
        grad=["X", "W"], out_slot="Out"),
})

# -- sequence ----------------------------------------------------------------


def _seq(r, b=3, t=5, d=4):
    x = _away(r, (b, t, d))
    sl = np.array([5, 3, 4], dtype="int32")
    return x, sl


def _seq_mask(sl, t):
    return (np.arange(t)[None, :] < sl[:, None])


SPECS.update({
    "sequence_pool": dict(
        ins=lambda r: dict(zip(("X", "SeqLen"), _seq(r))),
        attrs={"pooltype": "AVERAGE"},
        ref=lambda i, a: {"Out": (i["X"][0] * _seq_mask(
            i["SeqLen"][0], 5)[:, :, None]).sum(1) /
            i["SeqLen"][0][:, None]},
        grad=["X"]),
    "sequence_softmax": dict(
        ins=lambda r: {"X": _away(r, (3, 5)),
                       "SeqLen": np.array([5, 3, 4], "int32")},
        grad=["X"]),
    "sequence_first_step": dict(
        ins=lambda r: dict(zip(("X", "SeqLen"), _seq(r))),
        ref=lambda i, a: {"Out": i["X"][0][:, 0]},
        grad=["X"]),
    "sequence_last_step": dict(
        ins=lambda r: dict(zip(("X", "SeqLen"), _seq(r))),
        ref=lambda i, a: {"Out": i["X"][0][
            np.arange(3), i["SeqLen"][0] - 1]},
        grad=["X"]),
    "sequence_reverse": dict(
        ins=lambda r: dict(zip(("X", "SeqLen"), _seq(r))),
        grad=["X"], out_slot="Y"),
    "sequence_concat": dict(
        ins=lambda r: {"X": [_away(r, (3, 5, 2)), _away(r, (3, 5, 3))]},
        ref=lambda i, a: {"Out": np.concatenate(i["X"], axis=-1)},
        grad=["X"]),
    "sequence_expand": dict(
        ins=lambda r: {"X": _away(r, (3, 4)), "Y": _away(r, (3, 5, 2))},
        ref=lambda i, a: {"Out": np.repeat(i["X"][0][:, None, :], 5,
                                           axis=1)},
        grad=["X"]),
    "sequence_slice": dict(
        ins=lambda r: {"X": _away(r, (3, 5, 4)),
                       "Offset": np.array([[1], [0], [2]], "int64"),
                       "Length": np.array([[2], [2], [2]], "int64")},
        attrs={"length": 2},
        ref=lambda i, a: {"Out": np.stack([
            i["X"][0][b, int(i["Offset"][0][b, 0]):
                      int(i["Offset"][0][b, 0]) + 2]
            for b in range(3)])},
        grad=[]),
    "sequence_mask": dict(
        ins=lambda r: {"X": np.array([3, 1, 4], "int64")},
        attrs={"maxlen": 5},
        ref=lambda i, a: {"Y": _seq_mask(i["X"][0], 5)},
        grad=[], out_slot="Y"),
    "sequence_pad": dict(
        ins=lambda r: dict(zip(("X", "SeqLen"), _seq(r))),
        ref=lambda i, a: {"Out": i["X"][0]}, grad=["X"]),
    "sequence_erase": dict(
        ins=lambda r: {"X": _ints(r, (2, 6), 5),
                       "SeqLen": np.array([6, 4], "int32")},
        # a NONZERO erase token: erased positions become 0 != 2, so the
        # Out check distinguishes erase-to-zero from identity
        attrs={"tokens": [2]},
        ref=lambda i, a: {
            "Out": np.where(i["X"][0] == 2, 0, i["X"][0]),
            "Mask": (i["X"][0] != 2).astype("int32")},
        grad=[]),
    "lstm_unit": dict(
        ins=lambda r: {"X": _away(r, (3, 16)), "C_prev": _away(r, (3, 4))},
        grad=["X", "C_prev"], out_slot="H"),
    "gru_unit": dict(
        ins=lambda r: {"Input": _away(r, (3, 12)),
                       "HiddenPrev": _away(r, (3, 4)),
                       "Weight": _away(r, (4, 12)) * 0.3},
        grad=["Input", "HiddenPrev", "Weight"], out_slot="Hidden"),
    "dynamic_lstm": dict(
        ins=lambda r: {"Input": _away(r, (2, 3, 16)),
                       "Weight": _away(r, (4, 16)) * 0.3,
                       "SeqLen": np.array([3, 2], "int32")},
        grad=["Input", "Weight"], out_slot="Hidden"),
    "dynamic_gru": dict(
        ins=lambda r: {"Input": _away(r, (2, 3, 12)),
                       "Weight": _away(r, (4, 12)) * 0.3,
                       "SeqLen": np.array([3, 2], "int32")},
        grad=["Input", "Weight"], out_slot="Hidden"),
    "sequence_conv": dict(
        ins=lambda r: {"X": _away(r, (2, 4, 3)),
                       "Filter": _away(r, (9, 2)) * 0.3,
                       "SeqLen": np.array([4, 3], "int32")},
        attrs={"contextLength": 3, "contextStart": -1},
        grad=["X", "Filter"]),
})

# -- optimizers --------------------------------------------------------------


def _density_prior_ref(fh, fw, ih, iw, size, dens):
    """Grid of size x size priors, dens^2 per cell, normalized + clipped
    (density_prior_box_op.cc, single size / ratio 1)."""
    step_w, step_h = iw / fw, ih / fh
    offs = [((d + 0.5) / dens - 0.5) for d in range(dens)]
    boxes = np.zeros((fh, fw, dens * dens, 4), "float32")
    for y in range(fh):
        for x in range(fw):
            p = 0
            for dy in offs:
                for dx in offs:
                    cx = (x + 0.5) * step_w + dx * step_w
                    cy = (y + 0.5) * step_h + dy * step_h
                    boxes[y, x, p] = [(cx - size / 2) / iw,
                                      (cy - size / 2) / ih,
                                      (cx + size / 2) / iw,
                                      (cy + size / 2) / ih]
                    p += 1
    return np.clip(boxes, 0.0, 1.0)


def _roi_pool_ref(x, rois, ph, pw, scale):
    """Quantized-bin ROI max pool (roi_pool_op.cc)."""
    N, C, H, W = x.shape
    R = rois.shape[0]
    out = np.zeros((R, C, ph, pw), x.dtype)
    for r in range(R):
        b = int(rois[r, 0])
        x1, y1, x2, y2 = [np.round(v * scale) for v in rois[r, 1:]]
        rw = max(x2 - x1 + 1, 1.0)
        rh = max(y2 - y1 + 1, 1.0)
        for i in range(ph):
            hs = int(np.clip(np.floor(i * rh / ph) + y1, 0, H))
            he = int(np.clip(np.ceil((i + 1) * rh / ph) + y1, 0, H))
            for j in range(pw):
                ws = int(np.clip(np.floor(j * rw / pw) + x1, 0, W))
                we = int(np.clip(np.ceil((j + 1) * rw / pw) + x1, 0, W))
                if he > hs and we > ws:
                    out[r, :, i, j] = x[b, :, hs:he, ws:we].max((1, 2))
    return out


def _viterbi_ref(emission, transition, lengths):
    """Plain-numpy Viterbi per row (reference crf_decoding_op.h semantics:
    transition row 0 = start, row 1 = end, rows 2.. = [D, D])."""
    B, T, D = emission.shape
    start_w, end_w, trans = transition[0], transition[1], transition[2:]
    out = np.zeros((B, T), "int64")
    for b in range(B):
        L = int(lengths[b])
        v = start_w + emission[b, 0]
        bps = []
        for t in range(1, L):
            scores = v[:, None] + trans
            bps.append(scores.argmax(0))
            v = scores.max(0) + emission[b, t]
        tag = int((v + end_w).argmax())
        path = [tag]
        for bp in reversed(bps):
            tag = int(bp[tag])
            path.append(tag)
        out[b, :L] = path[::-1]
    return out


def _bipartite_ref(dist):
    """Greedy global bipartite matching (bipartite_match_op.cc): pick the
    best unused (row, col) pair repeatedly while positive."""
    N, M = dist.shape
    d = dist.copy()
    midx = np.full(M, -1, "int32")
    mdist = np.zeros(M, "float32")
    for _ in range(min(N, M)):
        r, c = np.unravel_index(d.argmax(), d.shape)
        if d[r, c] <= 0:
            break
        midx[c] = r
        mdist[c] = d[r, c]
        d[r, :] = -1e30
        d[:, c] = -1e30
    return midx, mdist


def _gather_tree_ref(ids, parents):
    B, T, K = ids.shape
    out = np.zeros_like(ids)
    for b in range(B):
        for k in range(K):
            beam = k
            for t in range(T - 1, -1, -1):
                out[b, t, k] = ids[b, t, beam]
                beam = parents[b, t, beam]
    return out


def _box_encode_ref(prior, target):
    def cs(b):
        w = b[:, 2] - b[:, 0]
        h = b[:, 3] - b[:, 1]
        return b[:, 0] + w / 2, b[:, 1] + h / 2, w, h
    pcx, pcy, pw, ph = cs(prior)
    tcx, tcy, tw, th = cs(target)
    dx = (tcx[:, None] - pcx[None, :]) / pw[None, :]
    dy = (tcy[:, None] - pcy[None, :]) / ph[None, :]
    dw = np.log(np.maximum(tw[:, None] / pw[None, :], 1e-10))
    dh = np.log(np.maximum(th[:, None] / ph[None, :], 1e-10))
    return np.stack([dx, dy, dw, dh], -1).astype("float32")


def _precision_recall_ref(indices, labels, n):
    tp = np.zeros(n); fp = np.zeros(n); fn = np.zeros(n)
    for i, l in zip(indices, labels):
        if i == l:
            tp[l] += 1
        else:
            fp[i] += 1
            fn[l] += 1
    prec = tp / np.maximum(tp + fp, 1e-12)
    rec = tp / np.maximum(tp + fn, 1e-12)
    f1 = 2 * prec * rec / np.maximum(prec + rec, 1e-12)
    mp = tp.sum() / max((tp + fp).sum(), 1e-12)
    mr = tp.sum() / max((tp + fn).sum(), 1e-12)
    mf = 2 * mp * mr / max(mp + mr, 1e-12)
    return np.array([prec.mean(), rec.mean(), f1.mean(), mp, mr, mf],
                    "float32")


def _cache_write_ref(cache, new, pos, axis):
    out = cache.copy()
    sl = [slice(None)] * cache.ndim
    sl[axis] = slice(pos, pos + 1)
    out[tuple(sl)] = new
    return out


def _mean_iou_ref(pred, label, n):
    cm = np.zeros((n, n))
    for pv, lv in zip(pred, label):
        cm[lv, pv] += 1
    inter = np.diag(cm)
    union = cm.sum(0) + cm.sum(1) - inter
    valid = union > 0
    iou = np.where(valid, inter / np.maximum(union, 1e-12), 0.0)
    # mismatches count against both the predicted and the label class
    # (mean_iou_op.h:95-97): OutWrong + OutCorrect == per-class union
    return {"OutMeanIou": np.float32(iou.sum() / max(valid.sum(), 1)),
            "OutWrong": (cm.sum(0) + cm.sum(1) - 2 * inter
                         ).astype("float32"),
            "OutCorrect": inter.astype("float32")}


def _opt_base(r, shape=(4, 3)):
    return {"Param": _away(r, shape), "Grad": _away(r, shape) * 0.1,
            "LearningRate": np.array([0.1], "float32")}


# numpy transcriptions of the reference's optimizer-op semantics
# (adam_op.h, adamax_op.h, adadelta_op.h, ftrl_op.h, proximal_adagrad_op.h,
# LAMB paper eq. as in the lowering's docstring) — independent of the jnp
# lowerings they check.

def _adam_ref(i, a):
    p, g = i["Param"][0], i["Grad"][0]
    m, v = i["Moment1"][0], i["Moment2"][0]
    b1p, b2p = i["Beta1Pow"][0], i["Beta2Pow"][0]
    b1, b2, eps = a["beta1"], a["beta2"], a["epsilon"]
    m2 = b1 * m + (1 - b1) * g
    v2 = b2 * v + (1 - b2) * g ** 2
    lr_t = 0.1 * np.sqrt(1 - b2p) / (1 - b1p)
    return {"ParamOut": p - lr_t * m2 / (np.sqrt(v2) + eps),
            "Moment1Out": m2, "Moment2Out": v2,
            "Beta1PowOut": b1p * b1, "Beta2PowOut": b2p * b2}


def _adamax_ref(i, a):
    p, g = i["Param"][0], i["Grad"][0]
    m, inf = i["Moment"][0], i["InfNorm"][0]
    b1p = i["Beta1Pow"][0]
    b1, b2, eps = a["beta1"], a["beta2"], a["epsilon"]
    m2 = b1 * m + (1 - b1) * g
    inf2 = np.maximum(b2 * inf, np.abs(g))
    return {"ParamOut": p - (0.1 / (1 - b1p)) * (m2 / (inf2 + eps)),
            "MomentOut": m2, "InfNormOut": inf2, "Beta1PowOut": b1p * b1}


def _adadelta_ref(i, a):
    p, g = i["Param"][0], i["Grad"][0]
    asg, asu = i["AvgSquaredGrad"][0], i["AvgSquaredUpdate"][0]
    rho, eps = a["rho"], a["epsilon"]
    g2 = rho * asg + (1 - rho) * g ** 2
    upd = -np.sqrt((asu + eps) / (g2 + eps)) * g
    return {"ParamOut": p + upd, "AvgSquaredGradOut": g2,
            "AvgSquaredUpdateOut": rho * asu + (1 - rho) * upd ** 2}


def _ftrl_ref(i, a):
    p, g = i["Param"][0], i["Grad"][0]
    sq, lin = i["SquaredAccumulator"][0], i["LinearAccumulator"][0]
    lr, l1, l2 = 0.1, a["l1"], a["l2"]
    new_sq = sq + g ** 2
    sigma = (np.sqrt(new_sq) - np.sqrt(sq)) / lr
    lin2 = lin + g - sigma * p
    denom = np.sqrt(new_sq) / lr + 2 * l2
    return {"ParamOut": (np.clip(lin2, -l1, l1) - lin2) / denom,
            "SquaredAccumOut": new_sq, "LinearAccumOut": lin2}


def _proximal_adagrad_ref(i, a):
    p, g, mom = i["Param"][0], i["Grad"][0], i["Moment"][0]
    lr, l1, l2 = 0.1, a["l1"], a["l2"]
    mom2 = mom + g ** 2
    alr = lr / np.sqrt(mom2)
    prox = p - alr * g
    return {"MomentOut": mom2,
            "ParamOut": np.sign(prox) * np.maximum(np.abs(prox) - alr * l1,
                                                   0.0) / (1.0 + alr * l2)}


def _lamb_ref(i, a):
    p, g = i["Param"][0], i["Grad"][0]
    m, v = i["Moment1"][0], i["Moment2"][0]
    b1p, b2p = i["Beta1Pow"][0], i["Beta2Pow"][0]
    b1, b2, eps = a["beta1"], a["beta2"], a["epsilon"]
    wd = a["weight_decay"]
    m2 = b1 * m + (1 - b1) * g
    v2 = b2 * v + (1 - b2) * g ** 2
    upd = (m2 / (1 - b1p)) / (np.sqrt(v2 / (1 - b2p)) + eps) + wd * p
    pn = np.sqrt(np.sum(p ** 2))
    un = np.sqrt(np.sum(upd ** 2))
    trust = pn / un if (pn > 0 and un > 0) else 1.0
    return {"ParamOut": p - 0.1 * trust * upd, "Moment1Out": m2,
            "Moment2Out": v2, "Beta1PowOut": b1p * b1,
            "Beta2PowOut": b2p * b2}


SPECS.update({
    "sgd": dict(
        ins=lambda r: _opt_base(r),
        ref=lambda i, a: {"ParamOut": i["Param"][0]
                          - 0.1 * i["Grad"][0]},
        grad=[], out_slot="ParamOut"),
    "momentum": dict(
        ins=lambda r: {**_opt_base(r), "Velocity": _away(r, (4, 3)) * 0.1},
        attrs={"mu": 0.9},
        ref=lambda i, a: {"ParamOut": i["Param"][0] - 0.1 * (
            0.9 * i["Velocity"][0] + i["Grad"][0])},
        grad=[]),
    "adam": dict(
        ins=lambda r: {**_opt_base(r),
                       "Moment1": _away(r, (4, 3)) * 0.1,
                       "Moment2": _pos(r, (4, 3)) * 0.01,
                       "Beta1Pow": np.array([0.9], "float32"),
                       "Beta2Pow": np.array([0.999], "float32")},
        attrs={"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8},
        ref=lambda i, a: _adam_ref(i, a),
        grad=[]),
    "adamax": dict(
        ins=lambda r: {**_opt_base(r),
                       "Moment": _away(r, (4, 3)) * 0.1,
                       "InfNorm": _pos(r, (4, 3)) * 0.1,
                       "Beta1Pow": np.array([0.9], "float32")},
        attrs={"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8},
        ref=lambda i, a: _adamax_ref(i, a),
        grad=[]),
    "adagrad": dict(
        ins=lambda r: {**_opt_base(r), "Moment": _pos(r, (4, 3)) * 0.01},
        attrs={"epsilon": 1e-6},
        ref=lambda i, a: {"ParamOut": i["Param"][0] - 0.1 * i["Grad"][0] /
                          (np.sqrt(i["Moment"][0] + i["Grad"][0] ** 2)
                           + 1e-6)},
        grad=[]),
    "decayed_adagrad": dict(
        ins=lambda r: {**_opt_base(r), "Moment": _pos(r, (4, 3)) * 0.01},
        attrs={"decay": 0.95, "epsilon": 1e-6},
        ref=lambda i, a: (lambda m2: {
            "MomentOut": m2,
            "ParamOut": i["Param"][0] - 0.1 * i["Grad"][0]
            / (np.sqrt(m2) + 1e-6)})(
                0.95 * i["Moment"][0] + 0.05 * i["Grad"][0] ** 2),
        grad=[]),
    "adadelta": dict(
        ins=lambda r: {"Param": _away(r, (4, 3)),
                       "Grad": _away(r, (4, 3)) * 0.1,
                       "AvgSquaredGrad": _pos(r, (4, 3)) * 0.01,
                       "AvgSquaredUpdate": _pos(r, (4, 3)) * 0.01},
        attrs={"rho": 0.95, "epsilon": 1e-6},
        ref=lambda i, a: _adadelta_ref(i, a),
        grad=[]),
    "rmsprop": dict(
        ins=lambda r: {**_opt_base(r),
                       "MeanSquare": _pos(r, (4, 3)) * 0.01,
                       "Moment": _away(r, (4, 3)) * 0.01},
        attrs={"decay": 0.95, "epsilon": 1e-6, "momentum": 0.9},
        ref=lambda i, a: (lambda ms: (lambda mom: {
            "MeanSquareOut": ms, "MomentOut": mom,
            "ParamOut": i["Param"][0] - mom})(
                0.9 * i["Moment"][0]
                + 0.1 * i["Grad"][0] / np.sqrt(ms + 1e-6)))(
                    0.95 * i["MeanSquare"][0] + 0.05 * i["Grad"][0] ** 2),
        grad=[]),
    "ftrl": dict(
        ins=lambda r: {**_opt_base(r),
                       "SquaredAccumulator": _pos(r, (4, 3)) * 0.01,
                       "LinearAccumulator": _away(r, (4, 3)) * 0.01},
        attrs={"l1": 0.01, "l2": 0.01, "lr_power": -0.5},
        ref=lambda i, a: _ftrl_ref(i, a),
        grad=[]),
    "proximal_gd": dict(
        ins=lambda r: _opt_base(r),
        attrs={"l1": 0.01, "l2": 0.01},
        ref=lambda i, a: (lambda prox: {
            "ParamOut": np.sign(prox)
            * np.maximum(np.abs(prox) - 0.1 * 0.01, 0.0)
            / (1.0 + 0.1 * 0.01)})(
                i["Param"][0] - 0.1 * i["Grad"][0]),
        grad=[]),
    "proximal_adagrad": dict(
        ins=lambda r: {**_opt_base(r), "Moment": _pos(r, (4, 3)) * 0.01},
        attrs={"l1": 0.01, "l2": 0.01},
        ref=lambda i, a: _proximal_adagrad_ref(i, a),
        grad=[]),
    "lamb": dict(
        ins=lambda r: {**_opt_base(r),
                       "Moment1": _away(r, (4, 3)) * 0.1,
                       "Moment2": _pos(r, (4, 3)) * 0.01,
                       "Beta1Pow": np.array([0.9], "float32"),
                       "Beta2Pow": np.array([0.999], "float32")},
        attrs={"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-6,
               "weight_decay": 0.01},
        ref=lambda i, a: _lamb_ref(i, a),
        grad=[]),
})

# -- random (statistical checks) --------------------------------------------
SPECS.update({
    "uniform_random": dict(
        ins=lambda r: {},
        attrs={"shape": [64, 64], "min": -2.0, "max": 2.0, "seed": 7},
        check=lambda got, i, a: (
            _assert(got["Out"][0].shape == (64, 64), "shape"),
            _assert(got["Out"][0].min() >= -2.0, "min bound"),
            _assert(got["Out"][0].max() <= 2.0, "max bound"),
            _assert(abs(got["Out"][0].mean()) < 0.1, "mean"))),
    "gaussian_random": dict(
        ins=lambda r: {},
        attrs={"shape": [64, 64], "mean": 1.0, "std": 2.0, "seed": 7},
        check=lambda got, i, a: (
            _assert(abs(got["Out"][0].mean() - 1.0) < 0.15, "mean"),
            _assert(abs(got["Out"][0].std() - 2.0) < 0.15, "std"))),
    "truncated_gaussian_random": dict(
        ins=lambda r: {},
        attrs={"shape": [64, 64], "mean": 0.0, "std": 1.0, "seed": 7},
        check=lambda got, i, a: (
            _assert(np.abs(got["Out"][0]).max() <= 2.0 + 1e-5,
                    "truncation at 2 std"))),
    "uniform_random_batch_size_like": dict(
        ins=lambda r: {"Input": _away(r, (5, 2))},
        attrs={"shape": [1, 7], "min": -1.0, "max": 1.0, "seed": 7},
        check=lambda got, i, a: _assert(
            got["Out"][0].shape == (5, 7), "batch-size-like shape")),
    "gaussian_random_batch_size_like": dict(
        ins=lambda r: {"Input": _away(r, (5, 2))},
        attrs={"shape": [1, 7], "seed": 7},
        check=lambda got, i, a: _assert(
            got["Out"][0].shape == (5, 7), "batch-size-like shape")),
    "sampling_id": dict(
        ins=lambda r: {"X": _softmax_np(r.rand(6, 4)).astype("float32")},
        attrs={"seed": 3},
        check=lambda got, i, a: _assert(
            ((got["Out"][0] >= 0) & (got["Out"][0] < 4)).all(),
            "ids in range")),
    "random_crop": dict(
        ins=lambda r: {"X": _away(r, (2, 3, 8, 8))},
        attrs={"shape": [3, 5, 5], "seed": 3},
        check=lambda got, i, a: _assert(
            got["Out"][0].shape == (2, 3, 5, 5), "crop shape")),
})


def _assert(cond, msg):
    assert cond, msg


# -- quantization / misc -----------------------------------------------------
SPECS.update({
    "fake_quantize_abs_max": dict(
        ins=lambda r: {"X": _away(r, (4, 6))},
        attrs={"bit_length": 8},
        # quantize-dequantize to the int8 grid at the abs-max scale
        ref=lambda i, a: (lambda s: {
            "Out": (np.round(i["X"][0] * (127 / s)) / (127 / s)
                    ).astype("float32"),
            "OutScale": np.float32(s)})(np.abs(i["X"][0]).max()),
        atol=1e-6, rtol=1e-5,
        grad=[]),
    "fake_dequantize_max_abs": dict(
        ins=lambda r: {"X": _ints(r, (4, 6), 127).astype("float32"),
                       "Scale": np.array([2.0], "float32")},
        attrs={"max_range": 127.0},
        ref=lambda i, a: {"Out": i["X"][0] * 2.0 / 127.0},
        grad=[]),
    "fake_quantize_moving_average_abs_max": dict(
        ins=lambda r: {"X": _away(r, (4, 6)),
                       "InScale": np.array([1.5], "float32"),
                       "InAccum": np.array([1.0], "float32"),
                       "InState": np.array([1.0], "float32")},
        attrs={"bit_length": 8, "moving_rate": 0.9},
        # scale = EMA(abs-max); quantize-dequantize at the EMA scale
        ref=lambda i, a: (lambda sc: {
            "OutScale": np.float32(sc),
            "Out": (np.round(i["X"][0] * (127 / sc)) / (127 / sc)
                    ).astype("float32")})(
            0.9 * 1.5 + 0.1 * np.abs(i["X"][0]).max()),
        atol=1e-6, rtol=1e-5,
        grad=[]),
    "piecewise_decay": dict(
        ins=lambda r: {"Step": np.array([150], "int64")},
        attrs={"boundaries": [100, 200], "values": [1.0, 0.5, 0.1]},
        ref=lambda i, a: {"Out": np.array(0.5, "float32")},
        grad=[]),
})

# -- metrics / eval ----------------------------------------------------------
SPECS.update({
    "accuracy": dict(
        ins=lambda r: {"Out": _softmax_np(r.rand(6, 4)).astype("float32"),
                       "Indices": _ints(r, (6, 1), 4),
                       "Label": _ints(r, (6, 1), 4)},
        check=lambda got, i, a: _assert(
            abs(float(got["Accuracy"][0]) -
                (i["Indices"][0] == i["Label"][0]).mean()) < 1e-6,
            "top-1 accuracy"),
        grad=[]),
    "auc": dict(
        ins=lambda r: {"Predict": _softmax_np(r.rand(8, 2))
                       .astype("float32"),
                       "Label": _ints(r, (8, 1), 2),
                       "StatPos": np.zeros(201, "int64"),
                       "StatNeg": np.zeros(201, "int64")},
        attrs={"num_thresholds": 200},
        check=lambda got, i, a: _assert(
            0.0 <= float(got["AUC"][0]) <= 1.0, "auc in [0,1]"),
        grad=[]),
    "precision_recall": dict(
        ins=lambda r: {"MaxProbs": r.rand(6, 1).astype("float32"),
                       "Indices": _ints(r, (6, 1), 3),
                       "Labels": _ints(r, (6, 1), 3)},
        attrs={"class_number": 3},
        ref=lambda i, a: {"BatchMetrics": _precision_recall_ref(
            i["Indices"][0].reshape(-1), i["Labels"][0].reshape(-1), 3)},
        atol=1e-5, rtol=1e-4,
        grad=[]),
    "mean_iou": dict(
        ins=lambda r: {"Predictions": _ints(r, (10,), 3),
                       "Labels": _ints(r, (10,), 3)},
        attrs={"num_classes": 3},
        ref=lambda i, a: _mean_iou_ref(i["Predictions"][0].reshape(-1),
                                       i["Labels"][0].reshape(-1), 3),
        grad=[]),
    "chunk_eval": dict(
        # hand-parsed IOB case (tag = type*2 + {0:B,1:I}; 4 = O/other):
        # row 0 (len 6): label B0 I0 O B1 I1 I1 = chunks {[0,1]t0,
        # [3,5]t1}, inference identical -> 2 correct. row 1 (len 4):
        # label B0 O B0 I0 = {[0]t0, [2,3]t0}; inference B0 I0 B0 I0 =
        # {[0,1]t0, [2,3]t0} -> only [2,3] matches (the first chunk's
        # END differs). Totals: infer 4, label 4, correct 3.
        ins=lambda r: {
            "Inference": np.array([[0, 1, 4, 2, 3, 3],
                                   [0, 1, 0, 1, 4, 4]], "int64"),
            "Label": np.array([[0, 1, 4, 2, 3, 3],
                               [0, 4, 0, 1, 4, 4]], "int64"),
            "Length": np.array([6, 4], "int64")},
        attrs={"num_chunk_types": 2, "chunk_scheme": "IOB"},
        ref=lambda i, a: {
            "Precision": np.array([0.75], "float32"),
            "Recall": np.array([0.75], "float32"),
            "F1-Score": np.array([0.75], "float32"),
            "NumInferChunks": np.array([4], "int64"),
            "NumLabelChunks": np.array([4], "int64"),
            "NumCorrectChunks": np.array([3], "int64")},
        grad=[]),
    "edit_distance": dict(
        ins=lambda r: {"Hyps": np.array([[1, 2, 3, 0]], "int64"),
                       "Refs": np.array([[1, 3, 3, 2]], "int64"),
                       "HypsLen": np.array([3], "int64"),
                       "RefsLen": np.array([4], "int64")},
        ref=lambda i, a: {"Out": np.array([[2.0]], "float32")},
        grad=[]),
    "ctc_align": dict(
        ins=lambda r: {"Input": np.array([[0, 1, 1, 0, 2, 2]], "int64"),
                       "InputLength": np.array([6], "int64")},
        attrs={"blank": 0, "padding_value": 0},
        check=lambda got, i, a: _assert(
            list(got["Output"][0].reshape(-1)[:2]) == [1, 2],
            "merged/blanked"),
        grad=[]),
    "linear_chain_crf": dict(
        ins=lambda r: {"Emission": _away(r, (2, 4, 3)) * 0.3,
                       "Transition": _away(r, (5, 3)) * 0.3,
                       "Label": _ints(r, (2, 4), 3),
                       "Length": np.array([4, 3], "int64")},
        grad=["Emission", "Transition"], out_slot="LogLikelihood"),
    "crf_decoding": dict(
        ins=lambda r: {"Emission": _away(r, (2, 4, 3)) * 0.3,
                       "Transition": _away(r, (5, 3)) * 0.3,
                       "Length": np.array([4, 3], "int64")},
        ref=lambda i, a: {"ViterbiPath": _viterbi_ref(
            i["Emission"][0], i["Transition"][0], i["Length"][0])},
        grad=[], out_slot="ViterbiPath"),
    "warpctc": dict(
        ins=lambda r: {"Logits": _away(r, (2, 5, 4)) * 0.3,
                       "Label": _ints(r, (2, 2), 3) + 1,
                       "LogitsLength": np.array([5, 4], "int64"),
                       "LabelLength": np.array([2, 1], "int64")},
        attrs={"blank": 0},
        grad=["Logits"], out_slot="Loss"),
    "gather_tree": dict(
        ins=lambda r: {"Ids": _ints(r, (3, 2, 4), 5),
                       "Parents": _ints(r, (3, 2, 4), 4)},
        ref=lambda i, a: {"Out": _gather_tree_ref(i["Ids"][0],
                                                  i["Parents"][0])},
        grad=[]),
    "beam_search": dict(
        # PreIds shifted off end_id so no beam is finished: the ref is a
        # plain flat top-k over accumulated log-probs
        ins=lambda r: {"PreIds": _ints(r, (2, 2), 5) + 1,
                       "PreScores": r.rand(2, 2).astype("float32"),
                       "Scores": np.log(_softmax_np(r.rand(2, 2, 5)))
                       .astype("float32")},
        attrs={"beam_size": 2, "end_id": 0},
        ref=lambda i, a: _beam_search_ref(i, a),   # defined below
        grad=[]),
})


def _beam_search_ref(i, a):
    pre_scores, scores = i["PreScores"][0], i["Scores"][0]
    B, K, V = scores.shape
    flat = (pre_scores[:, :, None] + scores).reshape(B, K * V)
    ids = np.zeros((B, K), "int64")
    par = np.zeros((B, K), "int64")
    sel = np.zeros((B, K), "float32")
    for b in range(B):
        idx = np.argsort(-flat[b], kind="stable")[:K]
        sel[b] = flat[b][idx]
        par[b] = idx // V
        ids[b] = idx % V
    return {"SelectedIds": ids, "SelectedScores": sel, "ParentIdx": par}

# -- detection ---------------------------------------------------------------


def _boxes(r, n):
    x1 = r.uniform(0, 0.5, (n,))
    y1 = r.uniform(0, 0.5, (n,))
    return np.stack([x1, y1, x1 + r.uniform(0.1, 0.5, (n,)),
                     y1 + r.uniform(0.1, 0.5, (n,))], -1).astype("float32")


def _iou_np_mat(b):
    """Pairwise IoU of one box set (the multiclass_nms ref's helper),
    replicating detection_ops._iou (clamped areas, union>0 guard)."""
    n = len(b)
    area = np.maximum(b[:, 2] - b[:, 0], 0) * np.maximum(
        b[:, 3] - b[:, 1], 0)
    out = np.zeros((n, n), "float32")
    for p in range(n):
        for q in range(n):
            xa = max(b[p, 0], b[q, 0])
            ya = max(b[p, 1], b[q, 1])
            xb = min(b[p, 2], b[q, 2])
            yb = min(b[p, 3], b[q, 3])
            inter = max(0, xb - xa) * max(0, yb - ya)
            union = area[p] + area[q] - inter
            out[p, q] = inter / max(union, 1e-10) if union > 0 else 0.0
    return out


def _multiclass_nms_ref(i, a):
    """Full numpy replica of the static-shape multiclass NMS lowering:
    per-class greedy suppression limited to nms_top_k selections, then a
    global keep_top_k sort with (label, score, box) rows padded -1."""
    NEG = -1e9
    bx, sc = i["BBoxes"][0], i["Scores"][0]
    B, C, M = sc.shape
    K = a["keep_top_k"]
    bg = a.get("background_label", 0)
    rows_all, nums = [], []
    for b in range(B):
        boxes = bx[b]
        iou = _iou_np_mat(boxes)
        kept = np.full((C, M), NEG, "float32")
        for c in range(C):
            if c == bg:
                continue
            valid = sc[b, c] > a["score_threshold"]
            s = np.where(valid, sc[b, c], NEG)
            keep = np.zeros(M, bool)
            alive = np.ones(M, bool)
            for _ in range(min(a["nms_top_k"], M)):
                idx = int(np.argmax(np.where(alive, s, NEG)))
                if alive[idx] and s[idx] > NEG / 2:
                    keep[idx] = True
                    alive = alive & ~(iou[idx] >= a["nms_threshold"])
                alive[idx] = False
            kept[c] = np.where(keep & valid, sc[b, c], NEG)
        flat = kept.reshape(-1)
        order = np.argsort(-flat, kind="stable")[:K]
        rows = np.full((K, 6), -1.0, "float32")
        cnt = 0
        for j, fi in enumerate(order):
            if flat[fi] > NEG / 2:
                rows[j, 0] = fi // M
                rows[j, 1] = flat[fi]
                rows[j, 2:] = boxes[fi % M]
                cnt += 1
        rows_all.append(rows)
        nums.append(cnt)
    return {"Out": np.stack(rows_all),
            "NmsRoisNum": np.array(nums, "int32")}


def _target_assign_ref(i, a):
    x, m = i["X"][0], i["MatchIndices"][0]
    B, M = m.shape
    K = x.shape[2]
    out = np.full((B, M, K), float(a.get("mismatch_value", 0)), x.dtype)
    w = np.zeros((B, M, 1), "float32")
    for b in range(B):
        for j in range(M):
            if m[b, j] >= 0:
                out[b, j] = x[b, m[b, j]]
                w[b, j, 0] = 1.0
    return {"Out": out, "OutWeight": w}


def _iou_ref(i, a):
    x, y = i["X"][0], i["Y"][0]
    out = np.zeros((len(x), len(y)), "float32")
    for p in range(len(x)):
        for q in range(len(y)):
            xa = max(x[p, 0], y[q, 0]); ya = max(x[p, 1], y[q, 1])
            xb = min(x[p, 2], y[q, 2]); yb = min(x[p, 3], y[q, 3])
            inter = max(0, xb - xa) * max(0, yb - ya)
            a1 = (x[p, 2] - x[p, 0]) * (x[p, 3] - x[p, 1])
            a2 = (y[q, 2] - y[q, 0]) * (y[q, 3] - y[q, 1])
            out[p, q] = inter / (a1 + a2 - inter)
    return {"Out": out}


SPECS.update({
    "iou_similarity": dict(
        ins=lambda r: {"X": _boxes(r, 4), "Y": _boxes(r, 3)},
        ref=_iou_ref, grad=[], atol=1e-4),
    "box_coder": dict(
        ins=lambda r: {"PriorBox": _boxes(r, 4),
                       "TargetBox": _boxes(r, 4)},
        attrs={"code_type": "encode_center_size"},
        ref=lambda i, a: {"OutputBox": _box_encode_ref(
            i["PriorBox"][0], i["TargetBox"][0])},
        atol=1e-4, rtol=1e-4,
        grad=[], out_slot="OutputBox"),
    "anchor_generator": dict(
        ins=lambda r: {"Input": _away(r, (1, 3, 2, 2))},
        attrs={"anchor_sizes": [64.0], "aspect_ratios": [1.0],
               "stride": [16.0, 16.0], "offset": 0.5},
        # one size x one ratio at stride 16: base 16x16 anchor scaled by
        # 64/16 -> 64x64 box centered at ((i+.5)*16, (j+.5)*16)
        ref=lambda i, a: {"Anchors": np.stack([np.stack([np.array(
            [(fx + 0.5) * 16 - 32, (fy + 0.5) * 16 - 32,
             (fx + 0.5) * 16 + 32, (fy + 0.5) * 16 + 32], "float32")
            for fx in range(2)]) for fy in range(2)])[:, :, None, :]},
        grad=[]),
    "prior_box": dict(
        ins=lambda r: {"Input": _away(r, (1, 3, 4, 4)),
                       "Image": _away(r, (1, 3, 32, 32))},
        attrs={"min_sizes": [4.0], "aspect_ratios": [1.0, 2.0]},
        check=lambda got, i, a: _assert(
            got["Boxes"][0].shape[-1] == 4 and
            (got["Boxes"][0] >= 0).all() and (got["Boxes"][0] <= 1).all(),
            "normalized boxes"),
        grad=[]),
    "density_prior_box": dict(
        ins=lambda r: {"Input": _away(r, (1, 3, 4, 4)),
                       "Image": _away(r, (1, 3, 32, 32))},
        attrs={"fixed_sizes": [4.0], "fixed_ratios": [1.0],
               "densities": [2]},
        ref=lambda i, a: {"Boxes": _density_prior_ref(4, 4, 32, 32, 4.0,
                                                      2)},
        atol=1e-5, rtol=1e-4,
        grad=[], out_slot="Boxes"),
    "bipartite_match": dict(
        ins=lambda r: {"DistMat": r.rand(4, 3).astype("float32")},
        ref=lambda i, a: dict(zip(
            ("ColToRowMatchIndices", "ColToRowMatchDist"),
            _bipartite_ref(i["DistMat"][0]))),
        grad=[]),
    "target_assign": dict(
        ins=lambda r: {"X": _away(r, (1, 4, 3)),
                       "MatchIndices": np.array([[0, -1, 2, 1]], "int32")},
        attrs={"mismatch_value": 0},
        ref=_target_assign_ref,
        grad=[]),
    "multiclass_nms": dict(
        ins=lambda r: {"BBoxes": np.tile(_boxes(r, 6)[None], (1, 1, 1)),
                       "Scores": _softmax_np(
                           r.rand(1, 3, 6), axis=1).astype("float32")},
        attrs={"score_threshold": 0.0, "nms_top_k": 4, "keep_top_k": 4,
               "nms_threshold": 0.5},
        ref=_multiclass_nms_ref, atol=1e-5,
        grad=[]),
    "roi_pool": dict(
        ins=lambda r: {"X": _away(r, (1, 2, 8, 8)),
                       "ROIs": np.array([[0, 0, 0, 7, 7],
                                         [0, 2, 2, 6, 6]], "float32")},
        attrs={"pooled_height": 2, "pooled_width": 2,
               "spatial_scale": 1.0},
        ref=lambda i, a: {"Out": _roi_pool_ref(
            i["X"][0], i["ROIs"][0], 2, 2, 1.0)},
        grad=[]),
    "ssd_loss": dict(
        # constructed optimum: prior 0 EQUALS the gt box (iou 1 -> matched;
        # encoded center-size targets all zero, so Location=0 gives zero
        # localization loss) and the confidence logits put +20 on each
        # prior's target class (gt label 1 on the matched prior, background
        # on the hard-mined negative) -> total loss ~= 2*log(1+2e^-20) ~ 0
        ins=lambda r: {
            "Location": np.zeros((1, 2, 4), "float32"),
            "Confidence": np.array([[[0., 20., 0.],
                                     [20., 0., 0.]]], "float32"),
            "GTBox": np.array([[[0.1, 0.1, 0.5, 0.5]]], "float32"),
            "GTLabel": np.array([[1]], "int64"),
            "PriorBox": np.array([[0.1, 0.1, 0.5, 0.5],
                                  [0.6, 0.6, 0.9, 0.9]], "float32")},
        ref=lambda i, a: {"Loss": np.float32(0.0)},
        atol=1e-5, out_slot="Loss",
        grad=[]),
    "rpn_target_assign": dict(
        ins=lambda r: {"Anchor": _boxes(r, 16), "GtBox": _boxes(r, 3)},
        attrs={"rpn_batch_size_per_im": 8, "rpn_fg_fraction": 0.5,
               "rpn_positive_overlap": 0.6, "rpn_negative_overlap": 0.3},
        check=lambda got, i, a: (
            _assert(set(np.unique(got["Labels"][0])) <= {-1, 0, 1},
                    "labels in {-1,0,1}"),
            _assert((got["Labels"][0] == 1).sum() >= 1,
                    "every gt owns at least one fg anchor"),
            _assert((got["Labels"][0] != -1).sum() <= 8,
                    "sampled set capped at rpn_batch_size_per_im")),
        grad=[]),
    "generate_proposals": dict(
        ins=lambda r: {"Scores": r.rand(2, 12).astype("float32"),
                       "BboxDeltas": (r.randn(2, 12, 4) * 0.1)
                       .astype("float32"),
                       "Anchors": _boxes(r, 12) * 20,
                       "ImInfo": np.array([[20, 20, 1.0], [20, 20, 1.0]],
                                          "float32")},
        attrs={"pre_nms_top_n": 8, "post_nms_top_n": 4,
               "nms_thresh": 0.7, "min_size": 0.1},
        check=lambda got, i, a: (
            _assert(got["RpnRois"][0].shape == (2, 4, 4), "roi shape"),
            _assert((got["RpnRoisNum"][0] >= 1).all(),
                    "at least one proposal per image")),
        grad=[]),
    "detection_map": dict(
        # detections == ground truth -> mAP must be exactly 1
        ins=lambda r: {"DetectRes": np.array(
            [[[1, 0.9, .1, .1, .4, .4], [2, 0.8, .5, .5, .9, .9]]],
            "float32"),
            "Label": np.array(
            [[[1, .1, .1, .4, .4], [2, .5, .5, .9, .9]]], "float32")},
        attrs={"class_num": 3, "overlap_threshold": 0.5},
        check=lambda got, i, a: _assert(
            abs(float(got["MAP"][0]) - 1.0) < 1e-6, "perfect mAP"),
        grad=[]),
    "positive_negative_pair": dict(
        # query 0: pairs (s=.9,l=2)>(s=.1,l=0) correct, (s=.5,l=1)>(.1,0)
        # correct, (.9,2)>(.5,1) correct -> 3 positive; query 1: one
        # inverted pair -> 1 negative
        ins=lambda r: {"Score": np.array(
            [[.9], [.5], [.1], [.2], [.7]], "float32"),
            "Label": np.array([[2], [1], [0], [1], [0]], "float32"),
            "QueryID": np.array([[0], [0], [0], [1], [1]], "int64")},
        ref=lambda i, a: {"PositivePair": np.array([3.0], "float32"),
                          "NegativePair": np.array([1.0], "float32"),
                          "NeutralPair": np.array([0.0], "float32")},
        grad=[]),
})

# -- 3-D conv/pool + sequence tail -------------------------------------------
SPECS.update({
    "conv3d_transpose": dict(
        ins=lambda r: {"Input": _away(r, (1, 2, 3, 3, 3)),
                       "Filter": _away(r, (2, 3, 2, 2, 2)) * 0.3},
        attrs={"strides": [2, 2, 2], "paddings": [0, 0, 0]},
        grad=["Input", "Filter"], out_slot="Output"),
    "pool3d": dict(
        ins=lambda r: {"X": r.rand(1, 2, 4, 4, 4).astype("float32")},
        attrs={"pooling_type": "avg", "ksize": [2, 2, 2],
               "strides": [2, 2, 2], "paddings": [0, 0, 0]},
        ref=lambda i, a: {"Out": i["X"][0].reshape(
            1, 2, 2, 2, 2, 2, 2, 2).mean(axis=(3, 5, 7))},
        grad=["X"]),
    "dynamic_lstmp": dict(
        ins=lambda r: {"Input": _away(r, (2, 3, 16)),
                       "Weight": _away(r, (3, 16)) * 0.3,
                       "ProjWeight": _away(r, (4, 3)) * 0.3,
                       "SeqLen": np.array([3, 2], "int32")},
        grad=["Input", "Weight", "ProjWeight"], out_slot="Projection"),
    "sequence_reshape": dict(
        ins=lambda r: {"X": _away(r, (2, 4, 6)),
                       "SeqLen": np.array([4, 2], "int32")},
        attrs={"new_dim": 3},
        ref=lambda i, a: {"Out": i["X"][0].reshape(2, 8, 3),
                          "SeqLenOut": np.array([8, 4], "int32")},
        grad=["X"]),
})


# ---------------------------------------------------------------------------
# exclusions & cross-references
# ---------------------------------------------------------------------------

# Control-flow / infra ops whose semantics need program context (sub-blocks,
# TensorArray environment, gradient machinery) — each has a dedicated test.
EXCLUDED = {
    "vjp_region": "autodiff machinery; exercised by every test via minimize",
    "cond_block": "needs sub-block program context; tests/test_control_flow.py",
    "lazy_cond": "needs sub-block program context; tests/test_control_flow.py",
    "while": "needs sub-block program context; tests/test_control_flow.py",
    "switch_case": "needs sub-block context; tests/test_control_flow.py",
    "static_rnn": "needs sub-block context; tests/test_control_flow.py",
    "array_read": "TensorArray env; tests/test_control_flow.py",
    "array_write": "TensorArray env; tests/test_control_flow.py",
    "array_length": "TensorArray env; tests/test_control_flow.py",
    "print": "side-effect op; tests/test_metrics_profiler.py",
    # test-probe op registered at tests/test_dataflow.py import (the
    # buffer-race detector's in-place alias fixture): visible here only
    # when the whole suite shares one process — not a product op
    "_tdf_inplace_bump": "tests/test_dataflow.py (test fixture)",
}

# Ops with dedicated per-op tests elsewhere (still directly checked).
COVERED_ELSEWHERE = {
    "isfinite": "tests/test_ops_math.py",
    # fusion subsystem: value-asserted against the unfused lowerings
    # (fwd + grad, xla + pallas-interpret backends) and end-to-end on
    # real programs through the fuse passes
    "fused_lstm": "tests/test_fusion.py",
    "fused_gru": "tests/test_fusion.py",
    "fused_decode_attention": "tests/test_fusion.py",
    # explicit gradient pipeline (registered when paddle_tpu.parallel is
    # imported): these lower collectives over the dp axis, so the harness
    # here (single-device, no shard_map context) cannot drive them —
    # parity + census + state tests live in the dedicated suites
    "dp_grad_comm": "tests/test_zero_comm.py",
    "dp_shard_slice": "tests/test_zero_comm.py",
    "dp_shard_all_gather": "tests/test_zero_comm.py",
    # pipeline-parallel executor (registered when paddle_tpu.parallel is
    # imported): pp_send/pp_recv lower to ppermute over the pp axis and
    # pp_pipeline_region runs the tick scan, so the single-device harness
    # cannot drive them — parity + HLO census + structure tests live in
    # the dedicated suites
    "pp_send": "tests/test_pipeline_parallel.py",
    "pp_recv": "tests/test_pipeline_parallel.py",
    "pp_pipeline_region": "tests/test_zpipeline_exec.py",
    # tp sharding subsystem (registered when paddle_tpu.parallel is
    # imported): the tp_* collectives/reshards lower psum/all_gather over
    # the tp axis with count-once custom VJPs, so the single-device harness
    # cannot drive them — propagation-rule units live in
    # test_sharding_prop.py, executor parity + census in test_ztp_exec.py
    "tp_allreduce": "tests/test_ztp_exec.py",
    "tp_ident": "tests/test_ztp_exec.py",
    "tp_split": "tests/test_ztp_exec.py",
    "tp_allgather": "tests/test_ztp_exec.py",
    "tp_vocab_lookup": "tests/test_ztp_exec.py",
    # paged KV serving (r20): pool-indexed cache write needs the block
    # table + pool program context — op parity + engine identity live in
    # the pager suite
    "paged_cache_write": "tests/test_kv_pager.py",
    # weight-only quantized serving (r21): payload+scale op pairs emitted
    # by quantize_params_pass — rewrite structure, dequant error bounds,
    # and decode parity live in the quant-serving suite
    "qmatmul": "tests/test_quant_serving.py",
    "qlookup": "tests/test_quant_serving.py",
    # int8 KV block pools (r22): the quantizing pool write needs the
    # block table + pool + scales program context — op behavior, engine
    # identity, and pool accounting live in the speculative suite
    "paged_cache_write_quant": "tests/test_speculative.py",
}


# ---------------------------------------------------------------------------
# the walker
# ---------------------------------------------------------------------------


def _registered():
    from paddle_tpu.framework.registry import registered_ops
    return registered_ops()


@pytest.mark.parametrize("op", sorted(SPECS))
def test_op(op):
    spec = SPECS[op]
    rng = np.random.RandomState(0)
    ins = spec["ins"](rng)
    attrs = spec.get("attrs", {})
    if callable(attrs):
        attrs = attrs(rng)
    is_test = spec.get("is_test", False)

    if spec.get("ref") is not None:
        # check_output runs the op and returns the outputs — one execution
        # serves both the parity check and the finite-smoke check below
        expected = spec["ref"](_np(ins), attrs)
        got = check_output(op, ins, expected, attrs,
                           atol=spec.get("atol", 1e-5),
                           rtol=spec.get("rtol", 1e-5), is_test=is_test)
    else:
        got = run_op(op, ins, attrs, is_test=is_test)
    # smoke: every float output must be finite
    for slot, vals in got.items():
        for v in vals:
            if np.issubdtype(np.asarray(v).dtype, np.floating):
                assert np.isfinite(v).all(), f"{op}: non-finite {slot}"

    if spec.get("check") is not None:
        spec["check"](got, _np(ins), attrs)

    reduce_fn = None
    if spec.get("reduce") == "weighted":
        import jax.numpy as jnp

        def reduce_fn(o):
            w = jnp.cos(jnp.arange(o.size, dtype=jnp.float32))
            return jnp.sum(o.reshape(-1) * w)
    for slot in spec.get("grad", []):
        check_grad(op, ins, [slot], out_slot=spec.get("out_slot", "Out"),
                   attrs=attrs, reduce_fn=reduce_fn,
                   atol=spec.get("grad_atol", 5e-3),
                   rtol=spec.get("grad_rtol", 5e-3))


def test_registry_fully_accounted():
    """Every registered op is directly checked here, checked by a named
    dedicated test, or excluded with a reason. The floors sit within 2 of
    the r7 actuals (212 direct / 212 value-asserted — every direct spec
    now carries a numpy ref, numeric-grad check, or property check), so
    CI guards the CURRENT state instead of lagging a round (VERDICT r5
    weak #4)."""
    ops = set(_registered())
    spec_ops = set(SPECS)
    unknown_specs = spec_ops - ops
    assert not unknown_specs, f"specs for unregistered ops: {unknown_specs}"
    unaccounted = ops - spec_ops - set(EXCLUDED) - set(COVERED_ELSEWHERE)
    assert not unaccounted, (
        f"{len(unaccounted)} registered ops have no direct check, no "
        f"dedicated test, and no exclusion reason: {sorted(unaccounted)}")
    strong = {op for op in spec_ops & ops
              if SPECS[op].get("ref") is not None
              or SPECS[op].get("grad")
              or SPECS[op].get("check") is not None}
    print(f"\nop coverage: {len(spec_ops & ops)} direct "
          f"({len(strong)} value-asserted) "
          f"+ {len(set(COVERED_ELSEWHERE) & ops)} dedicated "
          f"+ {len(set(EXCLUDED) & ops)} excluded "
          f"of {len(ops)} registered")
    assert len(spec_ops & ops) >= 210
    assert len(strong) >= 210, len(strong)


# ---------------------------------------------------------------------------
# static shape/dtype inference floors (framework/analysis.py)
# ---------------------------------------------------------------------------


def test_infer_spec_completeness_floor():
    """Every registered op is statically inferable — explicit infer_spec,
    engine-interpreted region op, or eval_shape over the lowering — or
    explicitly waived WITH a reason, and the covered fraction stays >= 90%.
    New ops can't silently skip static checking: registering one grows the
    registry, so it must either infer or join the documented waiver list."""
    import paddle_tpu.parallel  # noqa: F401 — registers the dp/pp ops
    from paddle_tpu.framework import analysis
    ops = set(_registered())
    covered, waived = analysis.infer_coverage()
    assert set(covered) | set(waived) == ops
    assert not (set(covered) & set(waived))
    for op, reason in waived.items():
        assert isinstance(reason, str) and reason, (
            f"waived op {op!r} must carry a reason")
    frac = len(covered) / len(ops)
    print(f"\ninfer coverage: {len(covered)}/{len(ops)} ({frac:.1%}), "
          f"{len(waived)} waived")
    assert frac >= 0.90, f"static inference covers only {frac:.1%}"


def test_infer_spec_shapes_match_references():
    """The inference rules are checked against the SAME spec table the
    numeric walker uses: for every op with a numpy reference, the
    statically inferred output shapes must equal the reference output
    shapes — one loop, not 200 parametrized cases, to keep tier-1 lean."""
    import jax
    from paddle_tpu.framework import analysis

    failures = []
    checked = 0
    for op in sorted(SPECS):
        spec = SPECS[op]
        if spec.get("ref") is None:
            continue
        rng = np.random.RandomState(0)
        ins = _np(spec["ins"](rng))
        attrs = spec.get("attrs", {})
        if callable(attrs):
            attrs = attrs(rng)
        if spec.get("is_test"):
            attrs = dict(attrs, is_test=True)
        in_structs = {k: [jax.ShapeDtypeStruct(v.shape, v.dtype)
                          for v in vs] for k, vs in ins.items()}
        expected = spec["ref"](ins, attrs)
        try:
            got = analysis.infer_op(op, in_structs, attrs)
        except Exception as e:  # noqa: BLE001
            failures.append(f"{op}: infer raised {type(e).__name__}: "
                            f"{str(e)[:120]}")
            continue
        for slot, exp in expected.items():
            exp = exp if isinstance(exp, list) else [exp]
            inferred = got.get(slot)
            if inferred is None:
                failures.append(f"{op}: slot {slot!r} not inferred")
                continue
            if len(inferred) != len(exp):
                failures.append(f"{op}.{slot}: inferred {len(inferred)} "
                                f"value(s) != reference {len(exp)}")
                continue
            def _strip_ends(s):
                # modulo LEADING/TRAILING size-1 dims only: the numeric
                # walker compares via assert_allclose, which broadcasts ()
                # against (1,) — but interior size-1 placement is load-
                # bearing ((3,1,2) vs (3,2,1) must still mismatch)
                s = list(s)
                while s and s[0] == 1:
                    s.pop(0)
                while s and s[-1] == 1:
                    s.pop()
                return tuple(s)

            for e_v, i_v in zip(exp, inferred):
                es = _strip_ends(np.shape(e_v))
                gs = _strip_ends(tuple(i_v.shape))
                if es != gs:
                    failures.append(
                        f"{op}.{slot}: inferred {tuple(i_v.shape)} != "
                        f"reference {tuple(np.shape(e_v))}")
        checked += 1
    print(f"\ninfer-vs-reference: {checked} ops value-checked")
    assert not failures, "\n".join(failures[:20])
    assert checked >= 150, checked
