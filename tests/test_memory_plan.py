"""Static memory planner tests (framework/memory_plan.py).

Five disciplines, mirroring ISSUE 14's acceptance bars:
1. coloring respects interference — property test over every
   MODEL_BUILDER x {plain, dp2, pp2, tp2}: every planned program passes
   verify_program with ZERO new diagnostics (the r13 buffer-reuse/WAR
   detectors are the soundness proof of the coloring), and every slot
   group is pairwise non-interfering against the SAME lifetime model the
   detector uses;
2. the schedule is a valid topological order of the def-use partial
   order (plus the ordered-chain contracts: collectives/rng keep their
   relative order, region segments precede their region);
3. fixed-seed loss parity planned-vs-unplanned (the segmented-remat
   execution recomputes the identical forward);
4. mutation tests — forcing two INTERFERING vars into one slot fires
   `buffer-reuse-race` BY NAME, and a slot crossing a region binder
   (sub-block var vs parent var live across the binder) fires the
   cross-block extension of the same code;
5. the PTPU_MEMORY_PLAN kill switch runs the strategy-requested plan
   unplanned (and sits in the executor's compile cache key).
"""

import numpy as np
import pytest

import jax

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core import flags as _flags
from paddle_tpu.core.enforce import EnforceError, InvalidArgumentError
from paddle_tpu.framework import analysis, dataflow, memory_plan
from paddle_tpu.framework.passes import get_pass
from paddle_tpu.parallel.grad_comm import comm_optimize_pass

import test_static_analysis as _tsa  # pytest puts tests/ on sys.path

_DP_CFG = {"shard_update": True, "quant": "", "block": 512,
           "error_feedback": False, "bucket_bytes": 1 << 20}


def _errors(diags):
    return [d for d in diags if d.severity == "error"]


def _codes(diags):
    return {d.code for d in _errors(diags)}


def _mlp_program(batch_cols=64):
    x = layers.data("x", shape=[batch_cols])
    label = layers.data("label", shape=[1], dtype="int64")
    h = layers.fc(x, size=128, act="relu")
    h2 = layers.fc(h, size=64, act="relu")
    loss = layers.mean(layers.softmax_with_cross_entropy(
        layers.fc(h2, size=10), label))
    pt.optimizer.MomentumOptimizer(0.1, momentum=0.9).minimize(loss)
    return pt.default_main_program(), loss


# ---------------------------------------------------------------------------
# 1. coloring respects interference: the builder x config property sweep
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("name", sorted(_tsa.MODEL_BUILDERS))
def test_planned_programs_verify_clean(name):
    """Every model builder, under every parallelism rewrite its gates
    admit, planned: zero error diagnostics (the sanitized apply already
    re-verified — this asserts the END state too), and every slot group
    is pairwise non-interfering under dataflow.interference_graph."""
    loss = _tsa.MODEL_BUILDERS[name]()
    if loss is not None:
        pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    prog = pt.default_main_program()
    configs = {"plain": lambda p: p}
    if loss is not None:
        configs["dp2"] = lambda p: comm_optimize_pass(p, 2, dict(_DP_CFG))
        configs["pp2"] = get_pass("pipeline_partition_pass", num_stages=2,
                                  num_microbatches=4, schedule="1f1b")
        from paddle_tpu.framework import sharding as _sharding
        if _sharding.has_tp_annotations(prog):
            configs["tp2"] = get_pass("tp_shard_pass", tp=2)
    for cname, apply in configs.items():
        try:
            rewritten = apply(prog)
        except (EnforceError, analysis.ProgramAnalysisError):
            continue                 # gate-rejected: config does not apply
        planned = get_pass("memory_plan_pass", time_budget_s=1.0)(rewritten)
        assert getattr(planned, "_memory_plan_applied", False)
        errs = _errors(analysis.verify_program(planned))
        assert not errs, (name, cname,
                          "\n".join(str(d) for d in errs))
        for block in planned.blocks:
            graph = dataflow.interference_graph(block)
            groups = {}
            for vn, v in block.vars.items():
                slot = getattr(v, "buffer_slot", None)
                if slot is not None:
                    groups.setdefault(slot, []).append(vn)
            for slot, members in groups.items():
                for i, a in enumerate(members):
                    for b in members[i + 1:]:
                        assert b not in graph.get(a, set()), (
                            name, cname, slot, a, b,
                            "slot members interfere")


# ---------------------------------------------------------------------------
# 2. the schedule is a valid topological order
# ---------------------------------------------------------------------------


def test_schedule_is_valid_topological_order():
    prog, _ = _mlp_program()
    block = prog.global_block()
    order = memory_plan.schedule_block(block, nominal_batch=8)
    if order is None:                # already optimal is a legal outcome
        order = list(range(len(block.ops)))
    assert sorted(order) == list(range(len(block.ops)))
    # RAW: every reader lands after its writer in the new order
    pos = {old: new for new, old in enumerate(order)}
    writers = {}
    for i, op in enumerate(block.ops):
        for nm in op.input_names():
            if nm in writers:
                assert pos[writers[nm]] < pos[i], (nm, writers[nm], i)
        for nm in op.output_names():
            writers[nm] = i
    # region segments all precede their region op
    for ridx, op in enumerate(block.ops):
        if op.type in dataflow.REGION_OPS:
            for i in op.attrs["fwd_ops"]:
                assert pos[i] < pos[ridx]


def test_schedule_never_regresses_predicted_peak():
    prog, loss = _mlp_program()
    before = analysis.peak_live_bytes(prog, nominal_batch=8)
    planned = get_pass("memory_plan_pass", remat=False)(prog)
    after = analysis.peak_live_bytes(planned, nominal_batch=8)
    assert after["peak_transient_bytes"] <= before["peak_transient_bytes"]


def test_scheduler_keeps_collective_relative_order():
    """dp_grad_comm and the other chained ops must keep their relative
    order (the r13 collective-order contract) — pinned by planning a
    dp-rewritten program and re-verifying."""
    prog, loss = _mlp_program()
    rewritten = comm_optimize_pass(prog, 2, dict(_DP_CFG))
    planned = get_pass("memory_plan_pass", time_budget_s=1.0)(rewritten)
    assert not _errors(analysis.verify_program(planned))
    # the comm op still sits between the region and every consumer
    block = planned.global_block()
    ridx = next(i for i, op in enumerate(block.ops)
                if op.type == "vjp_region")
    cidx = next(i for i, op in enumerate(block.ops)
                if op.type == "dp_grad_comm")
    assert ridx < cidx


# ---------------------------------------------------------------------------
# 3. fixed-seed loss parity planned vs unplanned
# ---------------------------------------------------------------------------


def _transformer_program():
    from paddle_tpu.models import transformer
    loss, _ = transformer.transformer_lm(
        vocab=64, max_len=8, d_model=32, d_inner=64, num_heads=4,
        num_layers=2, dropout=0.0, mean_loss=True)
    pt.optimizer.AdamOptimizer(1e-3).minimize(loss)
    return pt.default_main_program(), loss


def _train_losses(planned: bool, steps: int = 3):
    pt.reset_default_programs()
    pt.reset_global_scope()
    rng = np.random.RandomState(11)
    with pt.core.unique_name.guard():
        prog, loss = _transformer_program()
    if planned:
        prog = get_pass("memory_plan_pass", nominal_batch=8,
                        time_budget_s=1.0)(prog)
        rep = memory_plan.plan_report(prog)
        assert rep["remat"]["chosen"] == "remat", rep["remat"]
    exe = pt.Executor()
    pt.Executor().run(pt.default_startup_program())
    feed = {"tokens": rng.randint(0, 64, (8, 8)).astype("int64"),
            "tokens@SEQLEN": np.full((8,), 8, "int32"),
            "targets": rng.randint(0, 64, (8, 8)).astype("int64")}
    out = []
    for _ in range(steps):
        out.append(float(np.asarray(exe.run(
            program=prog, feed=feed, fetch_list=[loss],
            return_numpy=False)[0])))
    return out


def test_fixed_seed_loss_parity_planned_vs_unplanned():
    base = _train_losses(False)
    planned = _train_losses(True)
    assert np.allclose(base, planned, rtol=0, atol=1e-6), (base, planned)


def test_segmented_remat_executes_when_searched():
    """The chosen remat plan actually lands on the region (the parity
    test above then executes it): attrs present, a true partition of
    fwd_ops, live_out narrowed."""
    pt.reset_default_programs()
    with pt.core.unique_name.guard():
        prog, loss = _transformer_program()
    planned = get_pass("memory_plan_pass", time_budget_s=1.0)(prog)
    rop = next(op for op in planned.global_block().ops
               if op.type == "vjp_region")
    segs = rop.attrs.get("remat_segments")
    assert segs and sorted(i for s in segs for i in s) == \
        sorted(rop.attrs["fwd_ops"])
    assert rop.attrs.get("live_out") is not None


# ---------------------------------------------------------------------------
# 4. mutation tests: the detectors catch a bad plan BY NAME
# ---------------------------------------------------------------------------


def test_forcing_interfering_vars_into_one_slot_fires_by_name():
    """Two vars whose live intervals overlap, hand-forced into one slot:
    exactly `buffer-reuse-race` (the coloring's soundness gate — only
    this detector stands between a bad plan and silent corruption)."""
    x = layers.data("x", shape=[8])
    a = layers.fc(x, size=8)
    b = layers.fc(x, size=8)          # a still live (read below)
    layers.elementwise_add(a, b)
    prog = pt.default_main_program()
    blk = prog.global_block()
    blk.vars[a.name].buffer_slot = "forced#0"
    blk.vars[b.name].buffer_slot = "forced#0"
    assert _codes(analysis.verify_program(prog)) == {"buffer-reuse-race"}


def test_slot_across_region_binder_fires_by_name():
    """Satellite: a planner slot CROSSING a region binder — a sub-block
    var sharing a slot with a parent var that is live across the binder
    op — is verified through the binder chain and reports the exact
    `buffer-reuse-race` code (per-block scans cannot see this pair)."""
    x = layers.data("x", shape=[16])
    i = layers.fill_constant([1], "int64", 0)
    n = layers.fill_constant([1], "int64", 2)
    cond = layers.less_than(i, n)
    acc = layers.fc(x, size=16)        # parent transient, live across While
    w = layers.While(cond)
    with w.block():
        inner = layers.fc(acc, size=16)    # sub-block transient
        layers.increment(i, value=1.0, in_place=True)
        layers.less_than(i, n, cond=cond)
        inner_name = inner.name
    after = layers.fc(acc, size=4)     # keeps acc live PAST the binder
    prog = pt.default_main_program()
    blk0 = prog.global_block()
    sub = prog.blocks[1]
    blk0.vars[acc.name].buffer_slot = "xb#0"
    sub.vars[inner_name].buffer_slot = "xb#0"
    diags = analysis.verify_program(prog)
    assert _codes(diags) == {"buffer-reuse-race"}, diags
    msg = "\n".join(d.message for d in _errors(diags))
    assert "binder" in msg and inner_name in msg


def test_slot_in_sibling_branches_is_sanctioned():
    """Two sub-blocks of ONE binder (cond branches) are mutually
    exclusive — sharing a slot across them is legal."""
    from paddle_tpu.layers.control_flow import cond
    x = layers.data("x", shape=[8])
    flag = layers.fill_constant([1], "bool", True)
    names = []

    def _branch():
        t = layers.fc(x, size=8)
        names.append((pt.default_main_program()._current_block_idx,
                      t.name))
        return t

    cond(flag, _branch, _branch)
    prog = pt.default_main_program()
    (b1, n1), (b2, n2) = names
    assert b1 != b2
    prog.blocks[b1].vars[n1].buffer_slot = "sib#0"
    prog.blocks[b2].vars[n2].buffer_slot = "sib#0"
    assert not _errors(analysis.verify_program(prog))


def test_planner_slots_survive_clone():
    pt.reset_default_programs()
    with pt.core.unique_name.guard():
        prog, _ = _mlp_program()
    planned = get_pass("memory_plan_pass", time_budget_s=1.0)(prog)
    clone = planned.clone()
    slots = {n for b in planned.blocks for n, v in b.vars.items()
             if getattr(v, "buffer_slot", None) is not None}
    slots_c = {n for b in clone.blocks for n, v in b.vars.items()
               if getattr(v, "buffer_slot", None) is not None}
    assert slots == slots_c


# ---------------------------------------------------------------------------
# 5. kill switch + strategy plumbing
# ---------------------------------------------------------------------------


def test_kill_switch_runs_unplanned():
    from paddle_tpu.parallel import ParallelExecutor
    from paddle_tpu.parallel.strategy import BuildStrategy
    pt.reset_default_programs()
    pt.reset_global_scope()
    with pt.core.unique_name.guard():
        prog, loss = _mlp_program()
    bst = BuildStrategy()
    bst.memory_plan = True
    bst.memory_plan_time_budget_s = 1.0
    exe = ParallelExecutor(loss_name=loss.name, build_strategy=bst)
    try:
        _flags.set_flag("memory_plan", False)
        unplanned = exe.prepare_program(prog)
        assert not getattr(unplanned, "_memory_plan_applied", False)
    finally:
        _flags.set_flag("memory_plan", True)
    planned = exe.prepare_program(prog)
    assert getattr(planned, "_memory_plan_applied", False)
    rep = memory_plan.plan_report(planned)
    assert rep["predicted_peak_after"] <= rep["predicted_peak_before"]


def test_kill_switch_is_in_compile_cache_key():
    from paddle_tpu.framework.executor import _fusion_flags_key
    on = _fusion_flags_key()
    try:
        _flags.set_flag("memory_plan", False)
        off = _fusion_flags_key()
    finally:
        _flags.set_flag("memory_plan", True)
    assert on != off


def test_plan_report_requires_a_planned_program():
    pt.reset_default_programs()
    with pt.core.unique_name.guard():
        prog, _ = _mlp_program()
    with pytest.raises(InvalidArgumentError):
        memory_plan.plan_report(prog)


def test_plan_is_idempotent_and_never_mutates_the_input():
    pt.reset_default_programs()
    with pt.core.unique_name.guard():
        prog, _ = _mlp_program()
    v_before = prog._version
    ops_before = [op.type for op in prog.global_block().ops]
    planned = get_pass("memory_plan_pass", time_budget_s=1.0)(prog)
    assert prog._version == v_before
    assert [op.type for op in prog.global_block().ops] == ops_before
    assert not any(getattr(v, "buffer_slot", None) is not None
                   for b in prog.blocks for v in b.vars.values())
    again = get_pass("memory_plan_pass", time_budget_s=1.0)(planned)
    assert again is planned


def test_multi_region_programs_report_every_region():
    """Two losses over one trunk (two vjp_regions — lowering.build_plan
    supports them): the plan searches BOTH and the report carries every
    region's decision instead of silently keeping the last."""
    x = layers.data("x", shape=[32])
    label = layers.data("label", shape=[1], dtype="int64")
    h = layers.fc(x, size=64, act="relu")
    loss_a = layers.mean(layers.softmax_with_cross_entropy(
        layers.fc(h, size=10), label))
    loss_b = layers.mean(layers.fc(h, size=1))
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss_a)
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss_b)
    prog = pt.default_main_program()
    n_regions = sum(1 for op in prog.global_block().ops
                    if op.type == "vjp_region")
    assert n_regions == 2
    planned = get_pass("memory_plan_pass", time_budget_s=1.0)(prog)
    rep = memory_plan.plan_report(planned)
    assert rep["remat"] is None
    assert len(rep["remat_regions"]) == 2
    regions = {r["region"] for r in rep["remat_regions"]}
    assert len(regions) == 2


def test_sparse_embedding_regions_are_not_segmented():
    """A region with an is_sparse lookup keeps the un-segmented trace
    (selected-rows grads need it): the search must refuse."""
    ids = layers.data("ids", shape=[1], dtype="int64")
    label = layers.data("label", shape=[1], dtype="int64")
    emb = layers.embedding(ids, size=[100, 16], is_sparse=True)
    loss = layers.mean(layers.softmax_with_cross_entropy(
        layers.fc(emb, size=10), label))
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    planned = get_pass("memory_plan_pass",
                       time_budget_s=1.0)(pt.default_main_program())
    rop = next(op for op in planned.global_block().ops
               if op.type == "vjp_region")
    assert "remat_segments" not in rop.attrs
    rep = memory_plan.plan_report(planned)
    assert "sparse" in (rep["remat"].get("skipped") or "")
