"""Flash-attention kernel tests (pallas interpret mode on CPU) + fused op.

≙ SURVEY.md §7 stage 4 (Pallas kernels for hot ops). The kernel's tiling /
online-softmax logic is pinned against the XLA composite; gradients flow
through the custom VJP; the transformer uses the fused op when attention
dropout is off.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas_kernels import (_attention_reference,
                                           flash_attention)


def _qkv(rng, B=2, H=2, T=64, D=16):
    return (rng.randn(B, H, T, D).astype("float32") * 0.5,
            rng.randn(B, H, T, D).astype("float32") * 0.5,
            rng.randn(B, H, T, D).astype("float32"))


class TestFlashKernel:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_composite(self, rng, causal):
        q, k, v = _qkv(rng)
        ref = flash_attention(q, k, v, causal=causal, backend="xla")
        got = flash_attention(q, k, v, causal=causal, block_q=32,
                              block_k=16, backend="pallas_interpret")
        np.testing.assert_allclose(got, ref, atol=2e-5, rtol=2e-5)

    def test_uneven_block_sizes_padded_correctly(self, rng):
        q, k, v = _qkv(rng, T=48)
        ref = flash_attention(q, k, v, backend="xla")
        got = flash_attention(q, k, v, block_q=32, block_k=32,
                              backend="pallas_interpret")
        np.testing.assert_allclose(got, ref, atol=2e-5, rtol=2e-5)

    def test_cross_attention_lengths(self, rng):
        q = rng.randn(1, 2, 32, 16).astype("float32")
        k = rng.randn(1, 2, 64, 16).astype("float32")
        v = rng.randn(1, 2, 64, 16).astype("float32")
        ref = flash_attention(q, k, v, backend="xla")
        got = flash_attention(q, k, v, block_q=16, block_k=16,
                              backend="pallas_interpret")
        np.testing.assert_allclose(got, ref, atol=2e-5, rtol=2e-5)

    def test_softmax_stability_large_logits(self, rng):
        # online softmax must not overflow with large score magnitudes
        q, k, v = _qkv(rng, T=32, D=8)
        q = q * 30.0
        ref = flash_attention(q, k, v, backend="xla")
        got = flash_attention(q, k, v, block_q=16, block_k=16,
                              backend="pallas_interpret")
        assert np.isfinite(np.asarray(got)).all()
        np.testing.assert_allclose(got, ref, atol=1e-4, rtol=1e-4)


class TestFusedOpAndGrad:
    def test_op_lowering_and_custom_vjp(self, rng):
        from op_test import run_op
        q, k, v = _qkv(rng, T=32)
        out = run_op("fused_attention", {"Q": q, "K": k, "V": v},
                     attrs={"causal": True})["Out"][0]
        ref = _attention_reference(jnp.asarray(q), jnp.asarray(k),
                                   jnp.asarray(v), 1.0 / 4.0, True)
        np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)

    def test_gradients_match_composite(self, rng):
        from paddle_tpu.ops.pallas_kernels import _fused_attention
        q, k, v = _qkv(rng, B=1, H=1, T=16, D=8)
        scale = 1.0 / np.sqrt(8)

        def via_fused(q_, k_, v_):
            return jnp.sum(_fused_attention(q_, k_, v_, None, scale, True, "xla"))

        def via_ref(q_, k_, v_):
            return jnp.sum(_attention_reference(q_, k_, v_, scale, True))

        g1 = jax.grad(via_fused, argnums=(0, 1, 2))(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        g2 = jax.grad(via_ref, argnums=(0, 1, 2))(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5)

    def test_transformer_uses_fused_op_without_dropout(self, rng):
        import paddle_tpu as pt
        from paddle_tpu.models import transformer

        loss, logits = transformer.transformer_lm(
            vocab=50, max_len=16, d_model=32, num_heads=2, num_layers=1,
            d_inner=64, dropout=0.0)
        types = [op.type
                 for op in pt.default_main_program().global_block().ops]
        assert "fused_attention" in types

        pt.optimizer.AdamOptimizer(learning_rate=1e-3).minimize(loss)
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        toks = rng.randint(0, 50, (4, 16)).astype("int64")
        lab = rng.randint(0, 50, (4, 16)).astype("int64")
        sl = np.full((4,), 16, dtype="int32")
        feed = {"tokens": toks, "tokens@SEQLEN": sl, "targets": lab}
        l0 = exe.run(feed=feed, fetch_list=[loss])[0]
        for _ in range(5):
            l1 = exe.run(feed=feed, fetch_list=[loss])[0]
        assert np.isfinite(l1).all() and l1 < l0  # trains through the vjp


class TestFlashKernelEdgeCases:
    def test_causal_cross_attention_bottom_right_aligned(self, rng):
        """Regression: incremental-decode shape (Tq=1, Tk=64) must see all
        keys, matching the composite's bottom-right causal alignment."""
        q = rng.randn(1, 2, 1, 16).astype("float32")
        k = rng.randn(1, 2, 64, 16).astype("float32")
        v = rng.randn(1, 2, 64, 16).astype("float32")
        ref = flash_attention(q, k, v, causal=True, backend="xla")
        got = flash_attention(q, k, v, causal=True, block_q=8, block_k=16,
                              backend="pallas_interpret")
        np.testing.assert_allclose(got, ref, atol=2e-5, rtol=2e-5)

    def test_non_divisible_lengths_padded(self, rng):
        """Regression: T=200 with 128-blocks must pad+mask, not raise."""
        q, k, v = _qkv(rng, T=200, D=16)
        ref = flash_attention(q, k, v, causal=True, backend="xla")
        got = flash_attention(q, k, v, causal=True, block_q=128,
                              block_k=128, backend="pallas_interpret")
        np.testing.assert_allclose(got, ref, atol=2e-5, rtol=2e-5)


class TestFlashBackwardKernels:
    """FlashAttention-2-style backward: dq/dk/dv recomputed tile-wise from
    (q, k, lse) — gradients must match the composite exactly."""

    @pytest.mark.parametrize("shape,causal", [
        ((1, 2, 64, 64, 16), False),
        ((1, 2, 64, 64, 16), True),
        ((2, 1, 48, 48, 8), True),      # block padding path
        ((1, 1, 16, 64, 8), True),      # cross-attention decode shape
    ])
    def test_grads_match_composite(self, rng, shape, causal):
        from paddle_tpu.ops.pallas_kernels import _fused_attention
        B, H, T, Tk, D = shape
        q = (rng.randn(B, H, T, D) * 0.5).astype("float32")
        k = (rng.randn(B, H, Tk, D) * 0.5).astype("float32")
        v = rng.randn(B, H, Tk, D).astype("float32")
        g = jnp.asarray(rng.randn(B, H, T, D).astype("float32"))
        scale = 1.0 / np.sqrt(D)

        def f(backend):
            def fn(q_, k_, v_):
                return jnp.vdot(
                    _fused_attention(q_, k_, v_, None, scale, causal, backend), g)
            return jax.grad(fn, argnums=(0, 1, 2))(
                jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))

        for a, b in zip(f("xla"), f("pallas_interpret")):
            np.testing.assert_allclose(a, b, atol=3e-5, rtol=3e-5)

    def test_forward_lse_residual(self, rng):
        from paddle_tpu.ops.pallas_kernels import _flash_attention_pallas
        q, k, v = _qkv(rng, T=32, D=8)
        out, lse = _flash_attention_pallas(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            1.0 / np.sqrt(8), False, 16, 16, interpret=True, with_lse=True)
        # lse must equal logsumexp of the raw scores
        s = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(8)
        ref = np.log(np.sum(np.exp(s - s.max(-1, keepdims=True)), -1)) + \
            s.max(-1)
        np.testing.assert_allclose(lse, ref, atol=1e-5, rtol=1e-5)

    def test_no_visible_keys_rows_zero_on_all_backends(self, rng):
        """Regression: causal T > Tk leaves head query rows with no visible
        keys; both backends must output zeros there and agree on grads
        (the composite previously produced softmax's uniform-weight
        artifact)."""
        from paddle_tpu.ops.pallas_kernels import _fused_attention
        B, H, T, Tk, D = 1, 1, 8, 4, 4
        q = (rng.randn(B, H, T, D) * 0.5).astype("float32")
        k = (rng.randn(B, H, Tk, D) * 0.5).astype("float32")
        v = rng.randn(B, H, Tk, D).astype("float32")
        scale = 1.0 / np.sqrt(D)
        outs, grads = {}, {}
        for backend in ("xla", "pallas_interpret"):
            outs[backend] = _fused_attention(
                jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), None, scale,
                True, backend)
            grads[backend] = jax.grad(
                lambda q_, k_, v_: jnp.sum(_fused_attention(
                    q_, k_, v_, None, scale, True, backend) ** 2),
                argnums=(0, 1, 2))(jnp.asarray(q), jnp.asarray(k),
                                   jnp.asarray(v))
        # rows 0..T-Tk-1 see no keys: zero output
        np.testing.assert_array_equal(np.asarray(outs["xla"])[:, :, :T - Tk],
                                      0.0)
        np.testing.assert_allclose(outs["xla"], outs["pallas_interpret"],
                                   atol=2e-5)
        for a, b in zip(grads["xla"], grads["pallas_interpret"]):
            np.testing.assert_allclose(a, b, atol=3e-5, rtol=3e-5)


class TestSegmentIds:
    """Packed-batch (segment-id) masking in the flash kernel — the
    static-shape LoD translation (SURVEY §5). Semantics must match
    parallel.ring_attention: attend iff ids equal; composes with causal."""

    @staticmethod
    def _ragged_pack(rng, B, T, n_seqs=3):
        """Segment ids like [0,0,0,1,1,2,2,2,...] per row — a ragged pack
        of n_seqs sequences of uneven lengths."""
        ids = np.zeros((B, T), np.int32)
        for b in range(B):
            cuts = np.sort(rng.choice(np.arange(1, T), n_seqs - 1,
                                      replace=False))
            ids[b] = np.searchsorted(cuts, np.arange(T), side="right")
        return ids

    @pytest.mark.parametrize("causal", [False, True])
    def test_values_match_composite(self, rng, causal):
        q, k, v = _qkv(rng, B=2, H=2, T=64, D=16)
        seg = self._ragged_pack(rng, 2, 64)
        ref = flash_attention(q, k, v, causal=causal, backend="xla",
                              segment_ids=seg)
        got = flash_attention(q, k, v, causal=causal, block_q=32,
                              block_k=32, backend="pallas_interpret",
                              segment_ids=seg)
        np.testing.assert_allclose(got, ref, atol=2e-5, rtol=2e-5)

    def test_segment_isolation_vs_separate_calls(self, rng):
        """Ground truth, not just backend parity: a packed row [seq A | seq
        B] must equal attending A and B separately."""
        D = 8
        qa, ka, va = _qkv(rng, B=1, H=1, T=24, D=D)
        qb, kb, vb = _qkv(rng, B=1, H=1, T=40, D=D)
        q = np.concatenate([qa, qb], axis=2)
        k = np.concatenate([ka, kb], axis=2)
        v = np.concatenate([va, vb], axis=2)
        seg = np.concatenate([np.zeros((1, 24), np.int32),
                              np.ones((1, 40), np.int32)], axis=1)
        scale = 1.0 / np.sqrt(D)
        packed = flash_attention(q, k, v, scale=scale, causal=True,
                                 block_q=16, block_k=16,
                                 backend="pallas_interpret",
                                 segment_ids=seg)
        outa = flash_attention(qa, ka, va, scale=scale, causal=True,
                               backend="xla")
        outb = flash_attention(qb, kb, vb, scale=scale, causal=True,
                               backend="xla")
        np.testing.assert_allclose(packed[:, :, :24], outa, atol=2e-5,
                                   rtol=2e-5)
        np.testing.assert_allclose(packed[:, :, 24:], outb, atol=2e-5,
                                   rtol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_composite_ragged(self, rng, causal):
        from paddle_tpu.ops.pallas_kernels import _fused_attention
        B, H, T, D = 2, 2, 48, 8
        q = (rng.randn(B, H, T, D) * 0.5).astype("float32")
        k = (rng.randn(B, H, T, D) * 0.5).astype("float32")
        v = rng.randn(B, H, T, D).astype("float32")
        g = jnp.asarray(rng.randn(B, H, T, D).astype("float32"))
        seg = jnp.asarray(self._ragged_pack(rng, B, T))
        scale = 1.0 / np.sqrt(D)

        def f(backend):
            def fn(q_, k_, v_):
                return jnp.vdot(_fused_attention(
                    q_, k_, v_, seg, scale, causal, backend, 16, 16), g)
            return jax.grad(fn, argnums=(0, 1, 2))(
                jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))

        for a, b in zip(f("xla"), f("pallas_interpret")):
            np.testing.assert_allclose(a, b, atol=3e-5, rtol=3e-5)

    def test_matches_ring_attention_semantics(self, rng):
        """The kernel and parallel.ring_attention implement the same
        packed-batch contract: compare on an unsharded single 'ring'."""
        from paddle_tpu.parallel.ring_attention import _block_attn
        B, H, T, D = 1, 2, 32, 8
        q, k, v = _qkv(rng, B=B, H=H, T=T, D=D)
        seg = self._ragged_pack(rng, B, T)
        scale = 1.0 / np.sqrt(D)
        out = flash_attention(q, k, v, scale=scale, backend="xla",
                              segment_ids=seg)
        # ring-style reference: one block, segment bias applied
        same = seg[:, :, None] == seg[:, None, :]
        bias = np.where(same[:, None], 0.0, -1e30).astype("float32")
        import jax.numpy as jnp_
        m0 = jnp_.full((B, H, T), -1e30)
        l0 = jnp_.zeros((B, H, T))
        o0 = jnp_.zeros((B, T, H, D))
        qt = jnp_.asarray(q.transpose(0, 2, 1, 3))
        kt = jnp_.asarray(k.transpose(0, 2, 1, 3))
        vt = jnp_.asarray(v.transpose(0, 2, 1, 3))
        m, l, o = _block_attn(qt, kt, vt, jnp_.asarray(bias), m0, l0, o0,
                              scale)
        ring_out = (o / jnp_.maximum(l, 1e-20).transpose(0, 2, 1)[..., None]
                    ).transpose(0, 2, 1, 3)
        np.testing.assert_allclose(out, ring_out, atol=2e-5, rtol=2e-5)

    def test_cross_attention_segment_pair(self, rng):
        """(q_ids, kv_ids) pair with Tq != Tk."""
        D = 8
        q = (rng.randn(1, 1, 16, D) * 0.5).astype("float32")
        k = (rng.randn(1, 1, 32, D) * 0.5).astype("float32")
        v = rng.randn(1, 1, 32, D).astype("float32")
        q_ids = np.repeat(np.array([[0, 1]], np.int32), 8, axis=1)
        kv_ids = np.repeat(np.array([[0, 1]], np.int32), 16, axis=1)
        ref = flash_attention(q, k, v, backend="xla",
                              segment_ids=(q_ids, kv_ids))
        got = flash_attention(q, k, v, block_q=8, block_k=16,
                              backend="pallas_interpret",
                              segment_ids=(q_ids, kv_ids))
        np.testing.assert_allclose(got, ref, atol=2e-5, rtol=2e-5)

    def test_default_blocks_with_midrange_lengths(self, rng):
        """Regression: the 512/1024 default blocks must clamp to
        128-multiples for sequence lengths like 200/300 (a raw min() gave
        Mosaic-illegal ragged block shapes and broke the segment-id
        tiling precondition)."""
        from paddle_tpu.ops.pallas_kernels import _clamp_block
        assert _clamp_block(512, 300) == 384      # 128-multiple, >= T
        assert _clamp_block(1024, 200) == 256
        assert _clamp_block(512, 8192) == 512     # big T: full block
        assert _clamp_block(32, 64) == 32         # explicit small blocks
        q, k, v = _qkv(rng, B=1, H=2, T=300, D=16)
        seg = self._ragged_pack(rng, 1, 300)
        ref = flash_attention(q, k, v, causal=True, backend="xla",
                              segment_ids=seg)
        # default (unspecified) blocks through the interpret kernel
        got = flash_attention(q, k, v, causal=True,
                              backend="pallas_interpret", segment_ids=seg)
        np.testing.assert_allclose(got, ref, atol=2e-5, rtol=2e-5)

    def test_layer_routes_segment_ids(self, rng):
        """layers.fused_attention(segment_ids=...) lowers and runs."""
        import paddle_tpu as pt
        from paddle_tpu import layers
        q = layers.data(name="q", shape=[2, 32, 8])
        seg = layers.data(name="seg", shape=[32], dtype="int32")
        out = layers.fused_attention(q, q, q, causal=True, segment_ids=seg)
        exe = pt.Executor()
        qv = (rng.randn(1, 2, 32, 8) * 0.5).astype("float32")
        segv = self._ragged_pack(rng, 1, 32)
        got = exe.run(feed={"q": qv, "seg": segv}, fetch_list=[out])[0]
        ref = flash_attention(qv, qv, qv, causal=True, backend="xla",
                              segment_ids=segv)
        np.testing.assert_allclose(got, np.asarray(ref), atol=1e-5)
