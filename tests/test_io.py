"""Persistence tests (≙ reference tests/book/* train->save->load->infer loop
+ test_io unit coverage of save/load_vars/params/persistables)."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, models


def _train_mlp(rng, steps=15):
    loss, acc, logits = models.mnist.mlp(hidden_sizes=(32,), class_num=10)
    pt.optimizer.Adam(learning_rate=0.01).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    x = rng.rand(64, 784).astype("float32")
    y = rng.randint(0, 10, (64, 1)).astype("int64")
    for _ in range(steps):
        exe.run(feed={"img": x, "label": y}, fetch_list=[loss])
    return exe, loss, logits, x, y


def test_save_load_params_roundtrip(tmp_path, rng):
    exe, loss, logits, x, y = _train_mlp(rng)
    saved = pt.save_params(exe, str(tmp_path / "params"))
    assert len(saved) >= 4  # 2 fc layers x (w, b)
    before = {n: np.asarray(pt.global_scope().get(n)) for n in saved}

    # clobber parameters, reload, verify restored
    for n in saved:
        pt.global_scope().set_var(n, np.zeros_like(before[n]))
    loaded = pt.load_params(exe, str(tmp_path / "params"))
    assert loaded == saved
    for n in saved:
        np.testing.assert_array_equal(np.asarray(pt.global_scope().get(n)),
                                      before[n])


def test_save_load_persistables_resume(tmp_path, rng):
    """Saving persistables captures optimizer state: training resumes
    identically (≙ checkpoint/resume semantics, reference trainer.py:641)."""
    exe, loss, logits, x, y = _train_mlp(rng, steps=5)
    pt.save_persistables(exe, str(tmp_path / "ckpt"), filename="all.npz")
    ref1, = exe.run(feed={"img": x, "label": y}, fetch_list=[loss])

    # new scope, reload, re-run same step
    pt.reset_global_scope()
    pt.load_persistables(exe, str(tmp_path / "ckpt"), filename="all.npz")
    exe2 = pt.Executor()
    ref2, = exe2.run(feed={"img": x, "label": y}, fetch_list=[loss])
    np.testing.assert_allclose(ref1, ref2, rtol=1e-5)


def test_save_load_inference_model(tmp_path, rng):
    exe, loss, logits, x, y = _train_mlp(rng)
    pt.save_inference_model(str(tmp_path / "model"), ["img"], [logits], exe)

    # independent numpy forward from the saved params (fc-relu-fc)
    with np.load(str(tmp_path / "model" / "__params__.npz")) as d:
        params = {k: d[k] for k in d.files}
    ws = sorted([v for v in params.values() if v.ndim == 2],
                key=lambda a: -a.shape[0])  # (784,32) then (32,10)
    bs_ = {v.shape[0]: v for v in params.values() if v.ndim == 1}
    h = np.maximum(x[:8] @ ws[0] + bs_[ws[0].shape[1]], 0)
    expected = h @ ws[1] + bs_[ws[1].shape[1]]

    pt.reset_global_scope()
    pt.reset_default_programs()
    predictor = pt.Predictor(str(tmp_path / "model"))
    assert predictor.feed_names == ["img"]
    out, = predictor.run({"img": x[:8]})
    np.testing.assert_allclose(out, expected, rtol=1e-4, atol=1e-5)

    # pruning dropped the label path and optimizer ops
    optypes = [op.type for op in predictor.program.global_block().ops]
    assert "adam" not in optypes
    assert "softmax_with_cross_entropy" not in optypes


def test_inferencer_and_clone(tmp_path, rng):
    exe, loss, logits, x, y = _train_mlp(rng, steps=3)
    pt.save_inference_model(str(tmp_path / "m"), ["img"], [logits], exe)
    inf = pt.Inferencer(str(tmp_path / "m"))
    out, = inf.infer({"img": x[:4]})
    assert out.shape == (4, 10)
    p2 = inf._predictor.clone()
    out2, = p2.run({"img": x[:4]})
    np.testing.assert_allclose(out, out2, rtol=1e-6)


def test_predictor_rejects_bad_feed(tmp_path, rng):
    exe, loss, logits, x, y = _train_mlp(rng, steps=1)
    pt.save_inference_model(str(tmp_path / "m"), ["img"], [logits], exe)
    predictor = pt.Predictor(str(tmp_path / "m"))
    with pytest.raises(Exception):
        predictor.run({"wrong": x[:4]})


def test_save_as_bf16(tmp_path, rng):
    """≙ save_op save_as_fp16 attr — bf16 variant."""
    exe, loss, logits, x, y = _train_mlp(rng, steps=1)
    saved = pt.save_params(exe, str(tmp_path / "p16"), filename="p.npz",
                           save_as_bf16=True)
    with np.load(str(tmp_path / "p16" / "p.npz")) as data:
        # bf16 bit patterns stored as tagged uint16 (npz can't carry bf16)
        assert all(k.endswith("@BF16") and data[k].dtype == np.uint16
                   for k in data.files)
    loaded = pt.load_params(exe, str(tmp_path / "p16"), filename="p.npz")
    assert loaded == saved
    # loaded back as float32 per var dtype
    w = np.asarray(pt.global_scope().get(saved[0]))
    assert w.dtype == np.float32
