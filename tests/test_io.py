"""Persistence tests (≙ reference tests/book/* train->save->load->infer loop
+ test_io unit coverage of save/load_vars/params/persistables)."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, models

pytestmark = pytest.mark.quick  # run_ci.sh quick smoke tier


def _train_mlp(rng, steps=15):
    loss, acc, logits = models.mnist.mlp(hidden_sizes=(32,), class_num=10)
    pt.optimizer.Adam(learning_rate=0.01).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    x = rng.rand(64, 784).astype("float32")
    y = rng.randint(0, 10, (64, 1)).astype("int64")
    for _ in range(steps):
        exe.run(feed={"img": x, "label": y}, fetch_list=[loss])
    return exe, loss, logits, x, y


def test_save_load_params_roundtrip(tmp_path, rng):
    exe, loss, logits, x, y = _train_mlp(rng)
    saved = pt.save_params(exe, str(tmp_path / "params"))
    assert len(saved) >= 4  # 2 fc layers x (w, b)
    before = {n: np.asarray(pt.global_scope().get(n)) for n in saved}

    # clobber parameters, reload, verify restored
    for n in saved:
        pt.global_scope().set_var(n, np.zeros_like(before[n]))
    loaded = pt.load_params(exe, str(tmp_path / "params"))
    assert loaded == saved
    for n in saved:
        np.testing.assert_array_equal(np.asarray(pt.global_scope().get(n)),
                                      before[n])


def test_save_load_persistables_resume(tmp_path, rng):
    """Saving persistables captures optimizer state: training resumes
    identically (≙ checkpoint/resume semantics, reference trainer.py:641)."""
    exe, loss, logits, x, y = _train_mlp(rng, steps=5)
    pt.save_persistables(exe, str(tmp_path / "ckpt"), filename="all.npz")
    ref1, = exe.run(feed={"img": x, "label": y}, fetch_list=[loss])

    # new scope, reload, re-run same step
    pt.reset_global_scope()
    pt.load_persistables(exe, str(tmp_path / "ckpt"), filename="all.npz")
    exe2 = pt.Executor()
    ref2, = exe2.run(feed={"img": x, "label": y}, fetch_list=[loss])
    np.testing.assert_allclose(ref1, ref2, rtol=1e-5)


def test_save_load_inference_model(tmp_path, rng):
    exe, loss, logits, x, y = _train_mlp(rng)
    pt.save_inference_model(str(tmp_path / "model"), ["img"], [logits], exe)

    # independent numpy forward from the saved params (fc-relu-fc)
    with np.load(str(tmp_path / "model" / "__params__.npz")) as d:
        params = {k: d[k] for k in d.files}
    ws = sorted([v for v in params.values() if v.ndim == 2],
                key=lambda a: -a.shape[0])  # (784,32) then (32,10)
    bs_ = {v.shape[0]: v for v in params.values() if v.ndim == 1}
    h = np.maximum(x[:8] @ ws[0] + bs_[ws[0].shape[1]], 0)
    expected = h @ ws[1] + bs_[ws[1].shape[1]]

    pt.reset_global_scope()
    pt.reset_default_programs()
    predictor = pt.Predictor(str(tmp_path / "model"))
    assert predictor.feed_names == ["img"]
    out, = predictor.run({"img": x[:8]})
    np.testing.assert_allclose(out, expected, rtol=1e-4, atol=1e-5)

    # pruning dropped the label path and optimizer ops
    optypes = [op.type for op in predictor.program.global_block().ops]
    assert "adam" not in optypes
    assert "softmax_with_cross_entropy" not in optypes


def test_export_cold_load_round_trip(tmp_path, rng):
    """train -> save(export=True) -> reset everything -> cold-load the
    StableHLO artifact -> logits match the tracer-based Predictor, at a
    batch size never seen at export time (symbolic batch dim).
    ≙ reference paddle_inference_api.h:1 + api_impl.cc:126 + inference/io.cc
    (the servable artifact a fresh process loads without model code)."""
    exe, loss, logits, x, y = _train_mlp(rng)
    pt.save_inference_model(str(tmp_path / "model"), ["img"], [logits], exe,
                            export=True)
    assert (tmp_path / "model" / "__exported__.bin").exists()

    reference_out, = pt.Predictor(str(tmp_path / "model")).run(
        {"img": x[:8]})

    # cold process simulation: no programs, no scope, no tracer involved —
    # ExportedPredictor only deserializes StableHLO and calls it
    pt.reset_global_scope()
    pt.reset_default_programs()
    cold = pt.Predictor.from_exported(str(tmp_path / "model"))
    assert cold.feed_names == ["img"]
    out, = cold.run({"img": x[:8]})
    np.testing.assert_allclose(out, reference_out, rtol=1e-5, atol=1e-6)

    # polymorphic batch: a size never used at export/trace time
    out3, = cold.run({"img": x[:3]})
    np.testing.assert_allclose(out3, reference_out[:3], rtol=1e-5,
                               atol=1e-6)


def test_inferencer_and_clone(tmp_path, rng):
    exe, loss, logits, x, y = _train_mlp(rng, steps=3)
    pt.save_inference_model(str(tmp_path / "m"), ["img"], [logits], exe)
    inf = pt.Inferencer(str(tmp_path / "m"))
    out, = inf.infer({"img": x[:4]})
    assert out.shape == (4, 10)
    p2 = inf._predictor.clone()
    out2, = p2.run({"img": x[:4]})
    np.testing.assert_allclose(out, out2, rtol=1e-6)


def test_predictor_rejects_bad_feed(tmp_path, rng):
    exe, loss, logits, x, y = _train_mlp(rng, steps=1)
    pt.save_inference_model(str(tmp_path / "m"), ["img"], [logits], exe)
    predictor = pt.Predictor(str(tmp_path / "m"))
    with pytest.raises(Exception):
        predictor.run({"wrong": x[:4]})


def test_save_as_bf16(tmp_path, rng):
    """≙ save_op save_as_fp16 attr — bf16 variant."""
    exe, loss, logits, x, y = _train_mlp(rng, steps=1)
    saved = pt.save_params(exe, str(tmp_path / "p16"), filename="p.npz",
                           save_as_bf16=True)
    with np.load(str(tmp_path / "p16" / "p.npz")) as data:
        # bf16 bit patterns stored as tagged uint16 (npz can't carry bf16)
        assert all(k.endswith("@BF16") and data[k].dtype == np.uint16
                   for k in data.files)
    loaded = pt.load_params(exe, str(tmp_path / "p16"), filename="p.npz")
    assert loaded == saved
    # loaded back as float32 per var dtype
    w = np.asarray(pt.global_scope().get(saved[0]))
    assert w.dtype == np.float32


# ---------------------------------------------------------------------------
# reader pipeline layers (py_reader / recordio readers / decorators)
# ---------------------------------------------------------------------------

def test_py_reader_feeds_training(rng):
    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.layers.io import py_reader

    reader = py_reader(capacity=8, shapes=[(4, 8), (4, 1)],
                       dtypes=["float32", "int64"],
                       names=["px", "py"])
    h = layers.fc(pt.default_main_program().global_block().vars["px"],
                  size=4)
    loss = layers.mean(h)
    pt.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())

    def gen():
        for _ in range(5):
            yield [rng.rand(4, 8).astype("float32"),
                   rng.randint(0, 2, (4, 1)).astype("int64")]

    reader.decorate_sample_list_generator(gen)
    reader.start()
    n = 0
    for feed in reader:
        out = exe.run(feed={"px": feed["px"]}, fetch_list=[loss])
        n += 1
    assert n == 5


def test_open_recordio_file_roundtrip(rng, tmp_path):
    import numpy as np
    from paddle_tpu.data.recordio import RecordIOWriter
    from paddle_tpu.layers.io import batch, open_recordio_file, shuffle

    path = str(tmp_path / "data.recordio")
    xs = [rng.rand(3, 4).astype("float32") for _ in range(10)]
    ys = [rng.randint(0, 5, (1,)).astype("int64") for _ in range(10)]
    with RecordIOWriter(path) as w:
        for x, y in zip(xs, ys):
            w.write(x.tobytes() + y.tobytes())

    reader = open_recordio_file(path, shapes=[(3, 4), (1,)],
                                dtypes=["float32", "int64"],
                                names=["x", "y"])
    got = list(reader())
    assert len(got) == 10
    np.testing.assert_allclose(got[0]["x"], xs[0])
    np.testing.assert_array_equal(got[0]["y"], ys[0])

    # decorators compose: shuffle then batch
    batched = batch(shuffle(reader, buffer_size=10), batch_size=5)
    bs = list(batched())
    assert len(bs) == 2 and bs[0]["x"].shape == (5, 3, 4)


def test_preprocessor_transform(rng, tmp_path):
    from paddle_tpu.layers.io import Preprocessor

    def reader():
        for i in range(4):
            yield {"v": i}

    p = Preprocessor(reader)

    @p.def_transform
    def _double(sample):
        return {"v": sample["v"] * 2}

    assert [s["v"] for s in p()()] == [0, 2, 4, 6]


def test_new_datasets_readers():
    from paddle_tpu.data import datasets as D
    x, y = next(iter(D.flowers.train(n=2)()))
    assert x.shape == (3, 224, 224) and 0 <= y < 102
    rec = next(iter(D.movielens.train(n=2)()))
    assert len(rec) == 8 and 1 <= rec[-1] <= 5
    rec9 = next(iter(D.conll05.train(n=2)()))
    assert len(rec9) == 9   # word, 5 ctx slots, pred, mark, label
    words, mark, labels = rec9[0], rec9[7], rec9[8]
    assert len(words) == len(mark) == len(labels)
    assert all(len(c) == len(words) for c in rec9[1:6])
    toks, pol = next(iter(D.sentiment.train(n=2)()))
    assert pol in (0, 1)
    img, lbl = next(iter(D.voc2012.train(n=2)()))
    assert img.shape[1:] == lbl.shape
    src, tgt, nxt = next(iter(D.wmt14.train(n=2)()))
    assert len(tgt) == len(nxt)
    d, f1, f2 = next(iter(D.mq2007.train(n_queries=2)()))
    assert f1.shape == (46,) and d >= 1
    feats, rel = next(iter(D.mq2007.train(format="listwise",
                                          n_queries=2)()))
    assert feats.shape[1] == 46 and len(rel) == feats.shape[0]


def test_py_reader_reset_isolates_epochs(rng):
    """Regression: a producer still blocked mid-epoch must not leak stale
    samples (or its END sentinel) into the queue after reset()+start()."""
    import time
    from paddle_tpu.layers.io import PyReader

    r = PyReader(["a"], capacity=2)

    def gen_big():
        for i in range(100):
            yield {"a": ("old", i)}

    r.decorate_sample_list_generator(gen_big)
    r.start()
    it = iter(r)
    next(it)              # producer now blocked on the full queue
    r.reset()

    def gen_new():
        for i in range(3):
            yield {"a": ("new", i)}

    r.decorate_sample_list_generator(gen_new)
    r.start()
    got = [s["a"] for s in r]
    assert got == [("new", 0), ("new", 1), ("new", 2)]


def test_py_reader_producer_error_surfaces(rng):
    from paddle_tpu.layers.io import PyReader

    r = PyReader(["a"], capacity=4)

    def bad_gen():
        yield {"a": 1}
        raise RuntimeError("corrupt record")

    r.decorate_sample_list_generator(bad_gen)
    r.start()
    with pytest.raises(RuntimeError, match="corrupt record"):
        list(r)


def test_double_buffer_keeps_reader_contract(rng):
    from paddle_tpu.layers.io import batch, double_buffer

    def reader():
        for i in range(6):
            yield {"x": np.full((2,), i, dtype="float32")}

    buffered = double_buffer(reader)
    assert callable(buffered)
    vals = [f["x"] for f in buffered()]
    assert len(vals) == 6
    # composes with batch()
    b = list(batch(double_buffer(reader), batch_size=3)())
    assert len(b) == 2 and b[0]["x"].shape == (3, 2)


def test_spp_tiny_spatial_input(rng):
    """Regression: feature maps smaller than the finest pyramid grid must
    pool with overlapping (never empty) bins."""
    from op_test import run_op
    x = rng.rand(1, 2, 2, 2).astype("float32")
    out = run_op("spp", {"X": x}, attrs={"pyramid_height": 3})["Out"][0]
    assert out.shape == (1, 2 * (1 + 4 + 16))
    assert np.isfinite(out).all()


def test_wmt14_test_split_differs_from_train():
    from paddle_tpu.data import datasets as D
    tr = next(iter(D.wmt14.train(n=1)()))
    te = next(iter(D.wmt14.test(n=1)()))
    assert not np.array_equal(tr[0], te[0])


def test_py_reader_reset_stops_producer_thread():
    """Regression: reset() must signal the blocked producer to exit, not
    leak a thread per epoch."""
    import time
    from paddle_tpu.layers.io import PyReader

    r = PyReader(["a"], capacity=1)
    r.decorate_sample_list_generator(lambda: ({"a": i} for i in range(50)))
    r.start()
    t = r._thread
    next(iter(r))          # producer now blocked on the full queue
    r.reset()
    t.join(timeout=5)
    assert not t.is_alive()


def test_open_recordio_rejects_mismatched_shapes(tmp_path):
    import numpy as np
    import pytest as _pytest
    from paddle_tpu.data.recordio import RecordIOWriter
    from paddle_tpu.layers.io import open_recordio_file

    path = str(tmp_path / "d.recordio")
    with RecordIOWriter(path) as w:
        w.write(np.zeros(12, "float32").tobytes())
    bad = open_recordio_file(path, shapes=[(4,)], dtypes=["float32"],
                             names=["x"])
    with _pytest.raises(ValueError, match="misconfiguration"):
        list(bad())


def test_demo_trainer_flow(rng, tmp_path):
    """≙ reference train/demo/demo_trainer.cc: save the program pair from a
    model script, then a model-agnostic driver trains it (fresh programs,
    no model code)."""
    img = layers.data("img", shape=[16])
    label = layers.data("label", shape=[1], dtype="int64")
    loss = layers.mean(layers.softmax_with_cross_entropy(
        layers.fc(img, size=4), label))
    pt.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
    d = str(tmp_path / "prog")
    pt.io.save_program(d, feed_names=["img", "label"], fetch_names=[loss])

    # in-process driver path (the subprocess path is exercised via CI)
    pt.reset_default_programs()
    pt.reset_global_scope()
    main_p, startup_p, feeds, fetches = pt.io.load_program(d)
    exe = pt.Executor()
    exe.run(startup_p)
    feed = {"img": rng.rand(8, 16).astype("float32"),
            "label": rng.randint(0, 4, (8, 1)).astype("int64")}
    first = exe.run(main_p, feed=feed, fetch_list=fetches)[0]
    for _ in range(10):
        last = exe.run(main_p, feed=feed, fetch_list=fetches)[0]
    assert last < first  # the saved program trains: updates are inside it
