"""Executor-level tests for the explicit (ZeRO-style) gradient-comm
pipeline: ReduceStrategy.ReduceScatter + BuildStrategy.quant_comm.

Census assertions follow tests/test_comm_structure.py's discipline — byte
counts parsed from the partitioned optimized HLO, balanced against the
analytic formula EXACTLY — plus loss parity against the SPMD baseline,
error-feedback statefulness across steps and through the run_steps carry,
the PTPU_QUANT_COMM kill switch, and the 3-axis-mesh regression confirming
quantization only engages on the dp axis.

(Named test_zero_* so the heavyweight compiles in this file sort after the
whole suite; the fast unit half lives in tests/test_grad_comm.py.)
"""

import os
import re
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core import flags
from paddle_tpu.core.enforce import InvalidArgumentError
from paddle_tpu.parallel import ParallelExecutor
from paddle_tpu.parallel.mesh import DeviceMesh
from paddle_tpu.parallel.strategy import BuildStrategy, ReduceStrategy

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))
from probe_common import (census_wire_bytes, collective_census,  # noqa: E402
                          collective_wire_bytes)

DP = 8
# fc(64->128) + fc(128->10): w1/b1/w2 ride the sharded path (dim0 % 8 == 0),
# b2 [10] rides the bucket (padded to 16 f32 = 64 bytes)
GRAD_BYTES = (64 * 128 + 128 + 128 * 10 + 10) * 4
SHARDED_BYTES = (64 * 128 + 128 + 128 * 10) * 4
BUCKET_PAD_BYTES = 16 * 4


def _build_mlp(optimizer="momentum"):
    x = layers.data("x", shape=[64])
    label = layers.data("label", shape=[1], dtype="int64")
    h = layers.fc(x, size=128, act="relu")
    logits = layers.fc(h, size=10)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    if optimizer == "momentum":
        pt.optimizer.MomentumOptimizer(0.1, momentum=0.9).minimize(loss)
    else:
        pt.optimizer.AdamOptimizer(0.01).minimize(loss)
    return loss


def _feed(rng, bs=32):
    return {"x": rng.rand(bs, 64).astype("float32"),
            "label": rng.randint(0, 10, (bs, 1)).astype("int64")}


def _exe(loss, mode, quant="", ef=False, axes=None, bucket=None):
    bst = BuildStrategy()
    bst.reduce_strategy = mode
    bst.quant_comm = quant
    bst.comm_error_feedback = ef
    if bucket is not None:
        bst.comm_bucket_bytes = bucket
    mesh = DeviceMesh(jax.devices(), axes or {"dp": DP})
    return ParallelExecutor(loss_name=loss.name, mesh=mesh,
                            build_strategy=bst)


def _compiled_hlo(exe, feed):
    scope = pt.global_scope()
    cs = list(exe._cache.values())[-1]
    feed_vals = tuple(jnp.asarray(feed[n]) for n in cs.feed_names)
    ro = tuple(scope.get(n) for n in cs.ro_names)
    rw = tuple(scope.get(n) for n in cs.rw_names)
    return cs.fn.lower(feed_vals, ro, rw, np.uint32(0)).compile().as_text()


def _run_modes(rng, modes, steps=3, optimizer="momentum"):
    """Run the same training trajectory under each mode; returns
    {name: (losses, census)}. Fresh program/scope per mode."""
    feeds = [_feed(np.random.RandomState(1000 + i)) for i in range(steps)]
    out = {}
    for name, (mode, quant, ef) in modes.items():
        pt.reset_default_programs()
        pt.reset_global_scope()
        with pt.core.unique_name.guard():
            loss = _build_mlp(optimizer)
        exe = _exe(loss, mode, quant=quant, ef=ef)
        pt.Executor().run(pt.default_startup_program())
        losses = [float(exe.run(feed=f, fetch_list=[loss])[0])
                  for f in feeds]
        out[name] = (losses, collective_census(_compiled_hlo(exe, feeds[-1])))
    return out


class TestReduceScatterStructure:
    def test_census_no_gradient_allreduce_exact_balance(self, rng):
        res = _run_modes(rng, {
            "allreduce": (ReduceStrategy.AllReduce, "", False),
            "rs": (ReduceStrategy.ReduceScatter, "", False)})
        _, base_census = res["allreduce"]
        losses, census = res["rs"]

        # 1. no all-reduce carries gradient bytes: every surviving
        #    all-reduce is a scalar (loss pmean)
        for b, line in census.get("all-reduce", []):
            assert b <= 64, (b, line[:120])

        # 2. exact analytic balance. reduce-scatter: each sharded gradient
        #    leaves a 1/8 chunk, the bucket (b2 padded to 16 f32) too.
        rs_bytes = sum(b for b, _ in census.get("reduce-scatter", []))
        assert rs_bytes == SHARDED_BYTES // DP + BUCKET_PAD_BYTES // DP, \
            census.get("reduce-scatter")
        # all-gather: the three updated parameters come back whole, plus
        # the bucket's gathered gradient
        ag_bytes = sum(b for b, _ in census.get("all-gather", []))
        assert ag_bytes == SHARDED_BYTES + BUCKET_PAD_BYTES, \
            census.get("all-gather")

        # 3. ring identity, EXACT: an all-reduce costs its reduce-scatter +
        #    all-gather decomposition, so total wire bytes differ between
        #    the modes by precisely the bucket's pad-to-16-f32 slack
        #    (min_bytes=8 drops only the 4-byte scalar loss pmean both
        #    modes share). The GRADIENT share of the wire halves — the
        #    other half became parameter bytes (overlappable with the next
        #    forward pass, which an all-reduce's gather half is not).
        ar_wire = census_wire_bytes(base_census, DP, min_bytes=8)
        rs_wire = census_wire_bytes(census, DP, min_bytes=8)
        pad_bytes = BUCKET_PAD_BYTES - 10 * 4
        pad_wire = (collective_wire_bytes("reduce-scatter",
                                          pad_bytes // DP, DP)
                    + collective_wire_bytes("all-gather", pad_bytes, DP))
        assert rs_wire - ar_wire == pad_wire, (rs_wire, ar_wire, pad_wire)
        grad_wire = (collective_wire_bytes("reduce-scatter", rs_bytes, DP)
                     + collective_wire_bytes("all-gather", BUCKET_PAD_BYTES,
                                             DP))
        assert grad_wire < 0.51 * ar_wire, (grad_wire, ar_wire)

    def test_quantized_census_wire_ratio(self, rng):
        res = _run_modes(rng, {
            "allreduce": (ReduceStrategy.AllReduce, "", False),
            "quant": (ReduceStrategy.AllReduce, "int8", False)})
        _, base_census = res["allreduce"]
        losses, census = res["quant"]
        # int8 payload on the wire, fp32 nowhere except scalars
        assert any("s8[" in line for items in census.values()
                   for _, line in items), census
        base_wire = census_wire_bytes(base_census, DP, min_bytes=1024)
        q_wire = census_wire_bytes(census, DP, min_bytes=1024)
        ratio = base_wire / q_wire
        assert ratio >= 3.5, (base_wire, q_wire, ratio)
        # exact accounting of the quantized transfer: one bucket of all
        # 9610 grad values, padded to 9616 (dp) then per-chunk to 1280
        # (block 256): 8 destinations x (1280 int8 + 5 f32 scales)
        a2a = sum(b for b, _ in census.get("all-to-all", []))
        assert a2a == 8 * (1280 + 5 * 4), census.get("all-to-all")
        ag = sum(b for b, _ in census.get("all-gather", []))
        assert ag == 8 * (1280 + 5 * 4), census.get("all-gather")


class TestExplicitParity:
    def test_reduce_scatter_parity(self, rng):
        res = _run_modes(rng, {
            "allreduce": (ReduceStrategy.AllReduce, "", False),
            "rs": (ReduceStrategy.ReduceScatter, "", False)})
        base, _ = res["allreduce"]
        rs, _ = res["rs"]
        np.testing.assert_allclose(rs, base, rtol=0, atol=1e-5)

    def test_quantized_parity_with_error_feedback(self, rng):
        res = _run_modes(rng, {
            "allreduce": (ReduceStrategy.AllReduce, "", False),
            "q": (ReduceStrategy.ReduceScatter, "int8", True)},
            optimizer="adam")
        base, _ = res["allreduce"]
        q, _ = res["q"]
        np.testing.assert_allclose(q, base, rtol=0, atol=5e-3)


class TestErrorFeedback:
    def test_state_is_sharded_persistent_and_advances(self, rng):
        loss = _build_mlp()
        exe = _exe(loss, ReduceStrategy.ReduceScatter, quant="int8", ef=True)
        pt.Executor().run(pt.default_startup_program())
        exe.run(feed=_feed(rng), fetch_list=[loss])
        scope = pt.global_scope()
        err_names = [n for n in scope.local_var_names()
                     if n.startswith("dp_comm_err")]
        assert err_names, "error-feedback state vars missing from scope"
        first = {n: np.asarray(scope.get(n)).copy() for n in err_names}
        for n in err_names:
            v = first[n]
            assert v.shape[0] == DP, v.shape      # one residual per replica
            assert np.abs(v).sum() > 0            # quantization left residue
        exe.run(feed=_feed(np.random.RandomState(7)), fetch_list=[loss])
        changed = any(not np.array_equal(first[n],
                                         np.asarray(scope.get(n)))
                      for n in err_names)
        assert changed, "error state did not advance across steps"

    def test_run_steps_carries_error_state(self, rng):
        loss = _build_mlp()
        exe = _exe(loss, ReduceStrategy.ReduceScatter, quant="int8", ef=True)
        pt.Executor().run(pt.default_startup_program())
        feeds = [_feed(np.random.RandomState(i)) for i in range(3)]
        out = exe.run_steps(feeds, fetch_list=[loss])
        assert np.asarray(out[0]).shape[0] == 3   # stacked loss curve
        scope = pt.global_scope()
        err_names = [n for n in scope.local_var_names()
                     if n.startswith("dp_comm_err")]
        assert err_names
        assert np.abs(np.asarray(scope.get(err_names[0]))).sum() > 0


class TestGatesAndKillSwitch:
    def test_non_divisible_batch_rejected(self, rng):
        loss = _build_mlp()
        exe = _exe(loss, ReduceStrategy.ReduceScatter)
        pt.Executor().run(pt.default_startup_program())
        with pytest.raises(InvalidArgumentError, match="divisible"):
            exe.run(feed=_feed(rng, bs=30), fetch_list=[loss])

    def test_kill_switch_forces_fp32_wire(self, rng):
        loss = _build_mlp()
        exe = _exe(loss, ReduceStrategy.ReduceScatter, quant="int8")
        pt.Executor().run(pt.default_startup_program())
        old = flags.get_flag("quant_comm")
        try:
            flags.set_flag("quant_comm", False)
            feed = _feed(rng)
            exe.run(feed=feed, fetch_list=[loss])
            census = collective_census(_compiled_hlo(exe, feed))
            assert not any("s8[" in line for items in census.values()
                           for _, line in items), census
            # still the explicit pipeline: reduce-scatter present
            assert "reduce-scatter" in census, census.keys()
        finally:
            flags.set_flag("quant_comm", old)

    def test_sum_fetch_rejected_mean_fetch_ok(self, rng):
        x = layers.data("x", shape=[16])
        label = layers.data("label", shape=[1], dtype="int64")
        per_row = layers.softmax_with_cross_entropy(
            layers.fc(x, size=4), label)
        total = layers.reduce_sum(per_row)
        loss = layers.mean(per_row)
        pt.optimizer.SGDOptimizer(0.1).minimize(loss)
        exe = _exe(loss, ReduceStrategy.ReduceScatter)
        pt.Executor().run(pt.default_startup_program())
        feed = {"x": np.random.RandomState(0).rand(16, 16).astype("f4"),
                "label": np.zeros((16, 1), np.int64)}
        # a sum fetch would come back /dp — rejected, not silently scaled
        with pytest.raises(InvalidArgumentError, match="sum reduction"):
            exe.run(feed=feed, fetch_list=[loss, total])
        out = exe.run(feed=feed, fetch_list=[loss])   # mean fetch fine
        assert np.isfinite(float(out[0]))

    def test_general_mesh_annotation_replicated_here_is_allowed(self, rng):
        # a param annotated for a bigger mesh (tp axis) resolves to
        # all-None = replicated on this dp-only mesh: must NOT trip the
        # TP gate (mesh.pspec drops absent axes by design)
        loss = _build_mlp()
        prog = pt.default_main_program()
        w = next(v for v in prog.global_block().vars.values()
                 if getattr(v, "trainable", False) and len(v.shape) == 2)
        w.sharding_spec = ("tp", None)
        exe = _exe(loss, ReduceStrategy.ReduceScatter)
        pt.Executor().run(pt.default_startup_program())
        out = exe.run(feed=_feed(rng), fetch_list=[loss])
        assert np.isfinite(float(out[0]))

    def test_batch_global_op_rejected(self, rng):
        x = layers.data("img", shape=[16])
        h = layers.fc(x, size=16)
        h = layers.batch_norm(h)
        label = layers.data("label", shape=[1], dtype="int64")
        loss = layers.mean(layers.softmax_with_cross_entropy(
            layers.fc(h, size=4), label))
        pt.optimizer.SGDOptimizer(0.1).minimize(loss)
        exe = _exe(loss, ReduceStrategy.ReduceScatter)
        pt.Executor().run(pt.default_startup_program())
        with pytest.raises(InvalidArgumentError, match="batch_norm"):
            exe.run(feed={"img": np.zeros((16, 16), np.float32),
                          "label": np.zeros((16, 1), np.int64)},
                    fetch_list=[loss])


class TestThreeAxisMesh:
    def test_quantization_only_on_dp_axis(self, rng):
        """Regression: on a dp=2 x tp=2 x sp=2 mesh, every quantized
        collective must group dp siblings only — devices {i, i+4} for the
        (dp, tp, sp) axis order — and the numerics must match the SPMD
        baseline run on the same mesh."""
        feeds = [_feed(np.random.RandomState(50 + i), bs=16)
                 for i in range(2)]
        axes = {"dp": 2, "tp": 2, "sp": 2}

        pt.reset_default_programs()
        pt.reset_global_scope()
        with pt.core.unique_name.guard():
            loss = _build_mlp()
        exe = _exe(loss, ReduceStrategy.AllReduce, axes=axes)
        pt.Executor().run(pt.default_startup_program())
        base = [float(exe.run(feed=f, fetch_list=[loss])[0]) for f in feeds]

        pt.reset_default_programs()
        pt.reset_global_scope()
        with pt.core.unique_name.guard():
            loss = _build_mlp()
        exe = _exe(loss, ReduceStrategy.ReduceScatter, quant="int8",
                   axes=axes)
        pt.Executor().run(pt.default_startup_program())
        got = [float(exe.run(feed=f, fetch_list=[loss])[0]) for f in feeds]
        np.testing.assert_allclose(got, base, rtol=0, atol=1e-3)

        census = collective_census(_compiled_hlo(exe, feeds[-1]))
        dp_groups = {frozenset({i, i + 4}) for i in range(4)}
        quant_lines = [line for items in census.values()
                       for _, line in items if "s8[" in line]
        assert quant_lines, census
        for line in quant_lines:
            m = re.search(r"replica_groups=\{(\{[\d,]+\}(?:,\{[\d,]+\})*)\}",
                          line)
            assert m, line[:160]
            groups = {frozenset(int(x) for x in g.split(","))
                      for g in re.findall(r"\{([\d,]+)\}", m.group(1))}
            assert groups <= dp_groups, (groups, line[:160])
