"""Train a tiny character LM and generate from it with the KV-cache
beam-search decoder — the full train -> generate loop in one file.

    python examples/generate_text.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

import numpy as np

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import paddle_tpu as pt                                   # noqa: E402
from paddle_tpu.core import unique_name                   # noqa: E402
from paddle_tpu.framework.program import program_guard    # noqa: E402
from paddle_tpu.models import transformer                 # noqa: E402

TEXT = ("the quick brown fox jumps over the lazy dog and the dog barks "
        "at the quick brown fox while the lazy dog sleeps ") * 40
CHARS = sorted(set(TEXT))
V, T, D = len(CHARS) + 1, 32, 64           # +1 for BOS at id 0
ENC = {c: i + 1 for i, c in enumerate(CHARS)}
DEC = {i + 1: c for i, c in enumerate(CHARS)}


def batches(rng, b=32):
    ids = np.array([ENC[c] for c in TEXT], "int64")
    while True:
        starts = rng.randint(0, len(ids) - T - 1, (b,))
        toks = np.stack([ids[s:s + T] for s in starts])
        tgts = np.stack([ids[s + 1:s + T + 1] for s in starts])
        yield {"tokens": toks, "tokens@SEQLEN": np.full((b,), T, "int32"),
               "targets": tgts}


def main():
    rng = np.random.RandomState(0)
    loss, _ = transformer.transformer_lm(
        vocab=V, max_len=T, d_model=D, d_inner=128, num_heads=4,
        num_layers=2, dropout=0.0)
    pt.optimizer.AdamOptimizer(learning_rate=3e-3).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())

    it = batches(rng)
    for step in range(300):
        l = exe.run(feed=next(it), fetch_list=[loss])[0]
        if step % 100 == 0:
            print(f"step {step}: loss {float(l):.3f}")

    gen_prog = pt.Program()
    with program_guard(gen_prog, pt.Program()), unique_name.guard():
        seqs, scores = transformer.transformer_lm_generate(
            vocab=V, max_gen=48, d_model=D, d_inner=128, num_heads=4,
            num_layers=2, bos_id=ENC["t"], beam_size=1)
    out = exe.run(program=gen_prog,
                  feed={"prompt": np.full((1, 1), ENC["t"], "int64")},
                  fetch_list=[seqs])[0]
    text = "t" + "".join(DEC.get(int(i), "?") for i in out[0, :, 0])
    print("generated:", repr(text))


if __name__ == "__main__":
    main()
