"""Train an MNIST classifier end to end: build -> train -> evaluate ->
save -> reload -> serve one prediction.

    python examples/train_mnist.py          # CPU or TPU, ~1 min

Uses the real MNIST IDX files when cached under ~/.cache/paddle_tpu
(data.common.download verifies md5), synthetic digits offline.
"""
import os
import tempfile
import sys

sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

import numpy as np

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import paddle_tpu as pt                                   # noqa: E402
from paddle_tpu import layers                             # noqa: E402
from paddle_tpu.data import datasets                      # noqa: E402


def main():
    img = layers.data("img", shape=[784])
    label = layers.data("label", shape=[1], dtype="int64")
    h = layers.fc(img, size=200, act="relu")
    h = layers.fc(h, size=200, act="relu")
    logits = layers.fc(h, size=10)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    acc = layers.accuracy(logits, label)
    pt.optimizer.AdamOptimizer(learning_rate=1e-3).minimize(loss)

    exe = pt.Executor()
    exe.run(pt.default_startup_program())

    train = datasets.mnist.train()          # real IDX or synthetic fallback
    batch, bs = [], 64
    for epoch in range(1):
        for i, (x, y) in enumerate(train()):
            batch.append((x, y))
            if len(batch) < bs:
                continue
            xs = np.stack([b[0] for b in batch]).reshape(bs, 784)
            ys = np.array([b[1] for b in batch], "int64").reshape(bs, 1)
            batch = []
            l, a = exe.run(feed={"img": xs.astype("float32"), "label": ys},
                           fetch_list=[loss, acc])
            if i % 6400 < bs:
                print(f"epoch {epoch} step {i // bs}: "
                      f"loss {float(l):.3f} acc {float(a):.3f}")
            if i >= 12800:                  # a quick demo slice
                break

    d = os.path.join(tempfile.mkdtemp(), "mnist_model")
    pt.io.save_inference_model(d, ["img"], [logits], executor=exe)
    pred = pt.Predictor(d)
    probe = np.random.RandomState(0).rand(1, 784).astype("float32")
    print("reloaded predictor says:",
          int(np.argmax(pred.run({"img": probe})[0])))


if __name__ == "__main__":
    main()
