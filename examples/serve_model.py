"""Export a model and serve it three ways: in-process Predictor, the TCP
PredictorServer (clone-per-connection), and — when the native binary is
built — the pure-C++ `ptpu_predict --serve` speaking the same protocol.

    python examples/serve_model.py
    # optional native server: sh paddle_tpu/native/build.sh predict
"""
import os
import subprocess
import tempfile
import sys

sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

import numpy as np

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import paddle_tpu as pt                                   # noqa: E402
from paddle_tpu import layers                             # noqa: E402
from paddle_tpu.inferencer import Predictor               # noqa: E402
from paddle_tpu.serving import (PredictorClient,          # noqa: E402
                                PredictorServer)

NATIVE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "paddle_tpu", "native", "ptpu_predict")


def main():
    img = layers.data("img", shape=[8, 8, 1])
    conv = layers.conv2d(img, num_filters=8, filter_size=3, padding=1,
                         data_format="NHWC", act="relu")
    flat = layers.reshape(conv, shape=[-1, 8 * 8 * 8])
    logits = layers.fc(flat, size=10, act="softmax")
    exe = pt.Executor()
    exe.run(pt.default_startup_program())

    d = os.path.join(tempfile.mkdtemp(), "model")
    pt.io.save_inference_model(d, ["img"], [logits], executor=exe,
                               export=True, native=True)
    x = np.random.RandomState(0).rand(2, 8, 8, 1).astype("float32")

    # 1. cold-load the exported StableHLO artifact, no tracer in sight
    p = Predictor.from_exported(d)
    print("in-process:", p.run({"img": x})[0][0, :3])

    # 2. TCP server with pipelined requests
    with PredictorServer(p) as srv, \
            PredictorClient(*srv.address) as client:
        for _ in range(4):
            client.send({"img": x})
        outs = [client.recv()[0] for _ in range(4)]
        print("served (4 pipelined):", outs[0][0, :3])

    # 3. the same artifact from a pure-C++ process, same wire protocol
    if os.path.exists(NATIVE):
        proc = subprocess.Popen([NATIVE, d, "--serve", "0"],
                                stdout=subprocess.PIPE, text=True)
        try:
            port = int(proc.stdout.readline().split()[1])
            with PredictorClient("127.0.0.1", port) as client:
                print("C++ server:", client.infer({"img": x})[0][0, :3])
        finally:
            proc.kill()
    else:
        print("C++ server: build with `sh paddle_tpu/native/build.sh "
              "predict` to run this leg")


if __name__ == "__main__":
    main()
