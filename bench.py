"""Benchmark: ResNet-50 synthetic-ImageNet training throughput on one chip.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"}.
vs_baseline compares against the reference's best published in-repo ResNet-50
training number (84.08 images/sec, 2-socket Xeon 6148 MKL-DNN bs=256 —
reference benchmark/IntelOptimizedPaddle.md:39-45; the reference publishes no
Fluid-GPU tables, see BASELINE.md).
"""

from __future__ import annotations

import json
import time

import numpy as np

BASELINE_IMGS_PER_SEC = 84.08


def main():
    import jax
    import paddle_tpu as pt
    from paddle_tpu import models

    platform = jax.devices()[0].platform
    # TPU: full-size config; CPU fallback (no tunnel): tiny shapes so the
    # script stays runnable anywhere.
    on_accel = platform not in ("cpu",)
    batch = 128 if on_accel else 8
    depth = 50

    pt.reset_default_programs()
    pt.reset_global_scope()
    loss, acc, _ = models.resnet.resnet_imagenet(
        depth=depth, is_test=False, data_format="NHWC", use_bf16=True)
    opt = pt.optimizer.MomentumOptimizer(learning_rate=0.1, momentum=0.9)
    opt.minimize(loss)

    exe = pt.Executor()
    exe.run(pt.default_startup_program())

    rng = np.random.RandomState(0)
    img = rng.rand(batch, 224, 224, 3).astype("float32")
    label = rng.randint(0, 1000, (batch, 1)).astype("int64")
    # stage the batch on device once (a real input pipeline overlaps
    # host->device transfer via DevicePrefetcher; re-uploading the same
    # fixed batch every step would benchmark PCIe, not the chip)
    import jax.numpy as jnp
    feed = {"img": jnp.asarray(img), "label": jnp.asarray(label)}
    jax.block_until_ready(list(feed.values()))

    # warmup (compile + 2 steady steps)
    for _ in range(3):
        out = exe.run(feed=feed, fetch_list=[loss], return_numpy=False)
    jax.block_until_ready(out)

    iters = 20 if on_accel else 3
    t0 = time.time()
    for _ in range(iters):
        out = exe.run(feed=feed, fetch_list=[loss], return_numpy=False)
    jax.block_until_ready(out)
    dt = time.time() - t0

    imgs_per_sec = batch * iters / dt
    print(json.dumps({
        "metric": f"resnet50_train_images_per_sec_bs{batch}_{platform}",
        "value": round(imgs_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": round(imgs_per_sec / BASELINE_IMGS_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
