"""Benchmark: ResNet-50 synthetic-ImageNet training throughput on one chip.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline", "evidence"}.

vs_baseline compares against the reference's best published in-repo ResNet-50
training number (84.08 images/sec, 2-socket Xeon 6148 MKL-DNN bs=256 —
reference benchmark/IntelOptimizedPaddle.md:39-45; the reference publishes no
Fluid-GPU tables, see BASELINE.md).

The evidence block makes the headline auditable (≙ the hardware context the
reference publishes next to its tables, reference benchmark/README.md:33-39):
  - flops_per_step from XLA's own cost model (Executor.cost_analysis), so
    implied TFLOP/s and MFU vs the chip's bf16 peak can be checked;
  - loss_first/loss_last over the timed window with a convergent lr, so the
    timed steps are demonstrably real training (fwd+bwd+update), not a
    degenerate or dead-code-eliminated loop;
  - a DevicePrefetcher-fed variant over distinct host batches, so the input
    pipeline (host->device staging) is measured, not bypassed;
  - blocked per-step latency alongside pipelined throughput: the TPU tunnel
    has high dispatch latency, async pipelining through the functional state
    chain is what a real input loop achieves;
  - a Pallas flash-attention vs XLA-composite micro-bench (fwd+bwd), the
    number that justifies the hand-written kernel (SURVEY §7 stage 4).
"""

from __future__ import annotations

import json
import time

import numpy as np

BASELINE_IMGS_PER_SEC = 84.08
# reference's best published ResNet-50 INFERENCE number (bs16, same table)
INFER_BASELINE_IMGS_PER_SEC = 217.69

# (bf16 peak TFLOP/s, HBM GB/s) per chip generation (public spec sheets),
# keyed by substring of jax Device.device_kind.
_CHIP_SPECS = (
    ("v5 lite", 197.0, 819.0),   # TPU v5e
    ("v5e", 197.0, 819.0),
    ("v5p", 459.0, 2765.0),
    ("v6", 918.0, 1640.0),       # Trillium
    ("v4", 275.0, 1228.0),
)


def _chip_specs(device):
    kind = getattr(device, "device_kind", "") or ""
    for sub, peak, hbm in _CHIP_SPECS:
        if sub in kind.lower():
            return peak, hbm
    return None, None


def _build_resnet_train(batch: int, depth: int = 50):
    import paddle_tpu as pt
    from paddle_tpu import models

    pt.reset_default_programs()
    pt.reset_global_scope()
    # img declares uint8 staging: fp32 feeding (synthetic variant) compiles
    # with no cast; the prefetcher variant feeds uint8 so only 1/4 of the
    # fp32 bytes cross the host->device link, with the dequant compiled
    # into the step (layers.data staging_dtype, tests/test_staging.py)
    img = pt.layers.data(name="img", shape=[224, 224, 3],
                         staging_dtype="uint8")
    loss, acc, _ = models.resnet.resnet_imagenet(
        img=img, depth=depth, is_test=False, data_format="NHWC",
        use_bf16=True)
    # lr must be convergent at this batch size: the timed window doubles as
    # the work-verification window (loss must decrease during it).
    opt = pt.optimizer.MomentumOptimizer(learning_rate=3e-3, momentum=0.9)
    opt.minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    return exe, loss


N_DISTINCT_BATCHES = 8


def _staged_batches(batch: int, n: int = N_DISTINCT_BATCHES, seed: int = 0):
    """n DISTINCT pre-staged device batches with labels that are a real
    function of the images (mean-brightness bucket over 1000 classes), so
    every timed step does full fwd+bwd on data it has not necessarily seen
    and the task is learnable — the same audit property
    tools/bench_breadth.py carries (VERDICT r4 #4: the flagship number must
    not train on one staged batch)."""
    import jax.numpy as jnp

    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        label = rng.randint(0, 1000, (batch, 1)).astype("int64")
        # class id encoded as a global brightness offset (0.3 dynamic range
        # vs noise-mean sigma ~0.001): strong enough signal that the
        # 60-step timed window demonstrably learns across ALL 8 batches
        img = (rng.rand(batch, 224, 224, 3) * 0.7
               + (label / 1000.0)[:, :, None, None] * 0.3).astype("float32")
        out.append({"img": jnp.asarray(img), "label": jnp.asarray(label)})
    return out


def _resnet_throughput(batch: int, iters: int):
    """Pipelined steady-state throughput over 8 distinct pre-staged
    batches; returns (imgs/sec, blocked_step_ms, losses, flops_per_step,
    bytes_accessed, (exe, loss)).

    Sync discipline: the only barrier trusted is host-value realization
    (float(...) of a fetched loss) — through the remote-TPU tunnel,
    block_until_ready has been observed returning before execution completes,
    which is exactly the artifact that inflated the round-1 number. The loss
    of step k depends on step k-1's updated parameters, so realizing the
    final loss bounds all timed steps.
    """
    exe, loss = _build_resnet_train(batch)
    feeds = _staged_batches(batch)

    out = exe.run(feed=feeds[0], fetch_list=[loss], return_numpy=False)
    float(out[0])  # compile + drain: queue is empty past this point

    # blocked latency: one fully-synchronized step (dispatch + execute + fetch
    # round-trip)
    t0 = time.time()
    out = exe.run(feed=feeds[0], fetch_list=[loss], return_numpy=False)
    float(out[0])
    blocked_ms = (time.time() - t0) * 1e3

    # best of 3 windows: the dev tunnel's effective throughput swings with
    # ambient load; the fastest window is the least-interfered estimate of
    # the chip. Losses are tracked across ALL windows (training continues
    # through every one), so the work-verification property is unchanged.
    losses, dt = [], None
    for _ in range(3):
        fetched = []
        t0 = time.time()
        for i in range(iters):
            out = exe.run(feed=feeds[i % len(feeds)], fetch_list=[loss],
                          return_numpy=False)
            fetched.append(out[0])
        float(fetched[-1])  # realization barrier
        w = time.time() - t0
        dt = w if dt is None else min(dt, w)
        losses.extend(float(x) for x in fetched)

    ca = exe.cost_analysis(feed=feeds[0], fetch_list=[loss])
    flops = float(ca.get("flops", 0.0)) if ca else 0.0
    bytes_accessed = float(ca.get("bytes accessed", 0.0)) if ca else 0.0
    return (batch * iters / dt, blocked_ms, losses, flops, bytes_accessed,
            (exe, loss))


def _best_of(n_windows: int, window_fn):
    """max of n timing windows (tunnel load swings ~2x between sessions;
    the fastest window is the least-interfered estimate of the chip)."""
    best = None
    for _ in range(n_windows):
        rate = window_fn()
        best = rate if best is None else max(best, rate)
    return best


def interleaved_best(runners: dict, rounds: int = 3) -> dict:
    """{name: run_fn} -> {name: min seconds} over alternating rounds.
    Tunnel throughput drifts between windows; interleaving + per-side best
    keeps A/B comparisons fair (shared by the flash micro and
    tools/bench_longctx.py)."""
    best = {k: None for k in runners}
    for _ in range(rounds):
        for name, run in runners.items():
            dt = run()
            best[name] = dt if best[name] is None else min(best[name], dt)
    return best


def _link_reconciliation(link_samples, rate_per_sec,
                         wire_bytes_per_unit=224 * 224 * 3):
    """Shared link-utilization discipline (prefetcher + serving): capacity
    estimate = the FASTEST same-run link sample (the tunnel drifts 25%+
    within a session; the burst probe is a LOWER bound on capacity, so
    utilization can exceed 1.0 — meaning the sustained pipeline itself is
    the best link measurement available)."""
    link = float(np.max(link_samples))
    wire_mbps = rate_per_sec * wire_bytes_per_unit / 1e6
    return link, (wire_mbps / link if link else 0.0)


def _resnet_infer_throughput(batch: int = 16, iters: int = 30):
    """Inference img/s (is_test graph, batch-stat-free BN): the reference
    publishes ResNet-50 INFER bs16 = 217.69 img/s as its best in-repo
    number (reference benchmark/IntelOptimizedPaddle.md:81-87).

    Sync discipline: inference steps have no parameter-update chain, so a
    data dependency is created explicitly — step k's input derives from
    step k-1's output — making the final realization bound every timed
    step (same reasoning as the train bench; independent dispatches
    through the tunnel must not be trusted to complete in order)."""
    import jax.numpy as jnp

    import paddle_tpu as pt
    from paddle_tpu import models

    pt.reset_default_programs()
    pt.reset_global_scope()
    img = pt.layers.data(name="img", shape=[224, 224, 3],
                         staging_dtype="uint8")
    loss, acc, logits = models.resnet.resnet_imagenet(
        img=img, depth=50, is_test=True, data_format="NHWC", use_bf16=True)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.RandomState(3)
    img0 = jnp.asarray(rng.rand(batch, 224, 224, 3).astype("float32"))
    label = jnp.asarray(rng.randint(0, 1000, (batch, 1)).astype("int64"))
    out = exe.run(feed={"img": img0, "label": label}, fetch_list=[logits],
                  return_numpy=False)
    float(out[0][0, 0])

    def window():
        cur = img0
        t0 = time.time()
        out = None
        for _ in range(iters):
            out = exe.run(feed={"img": cur, "label": label},
                          fetch_list=[logits], return_numpy=False)
            # negligible (1e-30-scaled) but real dependency on the output
            cur = img0 + out[0][0, 0].astype(jnp.float32) * 1e-30
        float(out[0][0, 0])
        return batch * iters / (time.time() - t0)

    return _best_of(3, window)


def _resnet_served_throughput(batch: int = 16, n_requests: int = 32,
                              inflight: int = 8):
    """Server-mode inference throughput: a PredictorServer fields PIPELINED
    requests (≙ reference api_impl.cc:126 long-lived predictor; the
    conservative number below chains each request on the previous
    response, paying the full per-request round trip every time). With
    `inflight` requests outstanding on one connection, client IO, host->
    device staging (uint8 wire) and TPU compute overlap — the serving
    stack's real capacity."""
    import paddle_tpu as pt
    from paddle_tpu import models
    from paddle_tpu.serving import PredictorClient, PredictorServer

    pt.reset_default_programs()
    pt.reset_global_scope()
    img = pt.layers.data(name="img", shape=[224, 224, 3],
                         staging_dtype="uint8")
    loss, acc, logits = models.resnet.resnet_imagenet(
        img=img, depth=50, is_test=True, data_format="NHWC", use_bf16=True)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    program = pt.default_main_program()
    scope = pt.global_scope()

    class _Served:
        fetch_names = [logits.name]

        def run(self, feed, fetch_names=None, return_numpy=True):
            feed = dict(feed)
            feed.setdefault("label", np.zeros((batch, 1), "int64"))
            return exe.run(program=program, feed=feed,
                           fetch_list=list(fetch_names or self.fetch_names),
                           scope=scope, return_numpy=return_numpy)

    rng = np.random.RandomState(5)
    reqs = [(rng.rand(batch, 224, 224, 3) * 255).astype("uint8")
            for _ in range(4)]
    # same-run link sample (same uint8 wire format, prefetcher-style
    # concurrency) bracketing the serving windows: the serving number's
    # reconciliation metric (VERDICT r4 #8) — without it, 22 img/s next to
    # 658 direct reads as a 30x serving penalty when it is transport-bound
    link_samples = [_uint8_link_mbps(batch)]
    rates = []
    with PredictorServer(_Served()) as srv:
        host, port = srv.address
        with PredictorClient(host, port) as c:
            c.infer({"img": reqs[0]})  # compile + warm
            for _ in range(3):
                t0 = time.time()
                sent = recvd = 0
                while recvd < n_requests:
                    while sent < n_requests and sent - recvd < inflight:
                        c.send({"img": reqs[sent % len(reqs)]})
                        sent += 1
                    c.recv()
                    recvd += 1
                rates.append(batch * n_requests / (time.time() - t0))
    link_samples.append(_uint8_link_mbps(batch))
    best = max(rates)
    link, util = _link_reconciliation(link_samples, best)
    # per-window utilizations against the same link estimate: the
    # serving number's error bar (VERDICT r5 #4 — a 0.54-0.71 spread was
    # committed as a single point)
    utils = [_link_reconciliation(link_samples, r)[1] for r in rates]
    return best, link, util, utils


def _h2d_bandwidth_mbps(batch: int) -> float:
    """Host->device staging bandwidth for one image batch (the prefetcher
    variant is bounded by this; through the dev tunnel it is network-limited,
    on a real TPU host it is PCIe/DMA)."""
    import jax

    x = np.random.rand(batch, 224, 224, 3).astype("float32")
    d = jax.device_put(x)
    float(d[0, 0, 0, 0])
    t0 = time.time()
    for _ in range(2):
        d = jax.device_put(x)
        float(d[0, 0, 0, 0])
    dt = (time.time() - t0) / 2
    return x.nbytes / dt / 1e6


def _uint8_link_mbps(batch: int, streams: int = 4, reps: int = 12) -> float:
    """Raw h2d bandwidth for the PREFETCHER'S OWN wire format (a uint8
    image batch) at the SAME transfer concurrency the prefetcher uses.

    The dev tunnel is RTT/window-bound, not bandwidth-capped: measured
    12 MB/s single-stream vs 24+ MB/s at 3-4 concurrent streams
    (tools/probe_prefetch.py --exp streams). A single-stream denominator would
    understate the achievable link and let utilization exceed 1; matching
    the pipeline's concurrency makes the ratio honest."""
    import jax
    from concurrent.futures import ThreadPoolExecutor

    x = (np.random.RandomState(9).rand(batch, 224, 224, 3) * 255
         ).astype("uint8")
    d = jax.device_put(x)
    _ = np.asarray(d[0, 0, 0, 0])

    def put_one():
        h = jax.device_put(x)
        _ = np.asarray(h[0, 0, 0, 0])

    best = None
    with ThreadPoolExecutor(max_workers=streams) as pool:
        for _ in range(2):
            t0 = time.time()
            futs = [pool.submit(put_one) for _ in range(reps)]
            for f in futs:
                f.result()
            dt = time.time() - t0
            best = dt if best is None else min(best, dt)
    return x.nbytes * reps / best / 1e6


def _resnet_prefetcher_throughput(batch: int, iters: int, exe, loss):
    """Throughput with the real input pipeline: distinct host batches
    converted to uint8 on DevicePrefetcher's staging threads and put to
    device byte-lean (1/4 of the fp32 footprint), with the dequant compiled
    into the step. The uint8 feed signature compiles one new executable for
    the same (exe, loss) program; the warmup loop absorbs it.

    Returns (imgs_per_sec, link_MBps, utilization): the link is measured
    IMMEDIATELY before and after the fed windows with the same wire format
    and the same 4-stream concurrency, and utilization = fed wire rate /
    BEST link sample (see the capacity-estimate comment below) — the
    round-3 artifact divided a fed rate by a link measured in a DIFFERENT
    session of a tunnel that drifts ~2-5x, which is how 55 img/s read as
    47% of a link that no longer existed (VERDICT r3 weak #1)."""
    from paddle_tpu.data.feeder import staging_specs
    from paddle_tpu.data.prefetch import DevicePrefetcher

    rng = np.random.RandomState(1)
    host_batches = [
        {"img": rng.rand(batch, 224, 224, 3).astype("float32"),
         "label": rng.randint(0, 1000, (batch, 1)).astype("int64")}
        for _ in range(4)
    ]
    specs = staging_specs()  # img -> uint8 on the staging threads

    def feed_iter():
        for i in range(iters + 2):
            yield host_batches[i % len(host_batches)]

    link_samples = [_uint8_link_mbps(batch)]
    best = None
    for window in range(2):  # best of 2 (each pass restages every batch)
        pf = iter(DevicePrefetcher(feed_iter, capacity=8, staging=specs,
                                   stage_threads=4))
        for _ in range(2):  # warmup (compile happens on the very first)
            out = exe.run(feed=next(pf), fetch_list=[loss],
                          return_numpy=False)
        float(out[0])

        fetched = []
        t0 = time.time()
        for feed in pf:
            out = exe.run(feed=feed, fetch_list=[loss], return_numpy=False)
            fetched.append(out[0])
        float(fetched[-1])
        rate = batch * len(fetched) / (time.time() - t0)
        best = rate if best is None else max(best, rate)
        link_samples.append(_uint8_link_mbps(batch))
    link, util = _link_reconciliation(link_samples, best)
    return best, link, util


def _flash_attention_speedup(seq_len: int = 8192, heads: int = 8,
                             head_dim: int = 128, batch: int = 1):
    """Pallas flash attention vs the XLA composite, fwd+bwd wall clock.

    T=8192 is where the O(T) kernel earns its keep on a v5e: the composite's
    [T, T] score materialization pushes HBM to the limit (it OOMs outright at
    T=16384 where the flash kernel still runs)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops import pallas_kernels as pk

    rng = np.random.RandomState(2)
    shape = (batch, heads, seq_len, head_dim)
    q = jnp.asarray(rng.randn(*shape).astype(np.float32), dtype=jnp.bfloat16)
    k = jnp.asarray(rng.randn(*shape).astype(np.float32), dtype=jnp.bfloat16)
    v = jnp.asarray(rng.randn(*shape).astype(np.float32), dtype=jnp.bfloat16)
    scale = 1.0 / np.sqrt(head_dim)

    def loss_flash(q, k, v):
        return jnp.sum(pk.flash_attention(q, k, v, scale=scale, causal=True)
                       .astype(jnp.float32))

    def loss_ref(q, k, v):
        return jnp.sum(pk._attention_reference(q, k, v, scale, causal=True)
                       .astype(jnp.float32))

    def make(fn):
        g = jax.jit(jax.grad(fn, argnums=(0, 1, 2)))
        out = g(q, k, v)
        float(out[0][0, 0, 0, 0])  # compile + drain (realization barrier)

        def run():
            t0 = time.time()
            for _ in range(5):
                out = g(q, k, v)
            float(out[0][0, 0, 0, 0])  # device queue FIFO: bounds all 5
            return (time.time() - t0) / 5
        return run

    try:
        run_flash = make(loss_flash)
    except Exception as e:
        # surface the failure in the evidence — a broken kernel must not
        # silently read as "unavailable on this backend"
        return f"flash_error: {e!r:.120}"
    try:
        run_ref = make(loss_ref)
    except Exception:
        return "xla_oom"  # composite cannot even run at this T
    # interleaved rounds: tunnel throughput drifts between windows, and a
    # sequential flash-then-composite measurement can flip the ratio in
    # either direction; alternating rounds + per-side best cancels it
    t_flash = t_ref = None
    try:
        for _ in range(3):
            tf, tr = run_flash(), run_ref()
            t_flash = tf if t_flash is None else min(t_flash, tf)
            t_ref = tr if t_ref is None else min(t_ref, tr)
    except Exception:
        # a mid-measurement OOM (allocation drift) must degrade to the
        # documented marker, not abort the whole benchmark
        if t_flash is None:
            return "flash_error: runtime"
        return "xla_oom"
    # emit the raw per-side times: a bare ratio is unauditable when the
    # tunnel stalls one side's windows (observed: ratio 1.3x-10x across
    # sessions at identical shapes; BENCH_LONGCTX carries the canonical
    # interleaved curve)
    return {"speedup": round(t_ref / t_flash, 3),
            "flash_ms": round(t_flash * 1e3, 2),
            "composite_ms": round(t_ref * 1e3, 2)}


def _dp_comm_wire_evidence(dp: int = 8) -> dict:
    """Per-device gradient bytes-on-wire per step for the current default
    main program (the last-built ResNet train step) under the three
    reduce modes — ring accounting, parallel/grad_comm.py's model."""
    import paddle_tpu as pt
    from paddle_tpu.parallel.collective import compressed_size_ratio
    from paddle_tpu.parallel.grad_comm import spmd_allreduce_wire_bytes

    ar = spmd_allreduce_wire_bytes(pt.default_main_program(), dp)
    g = ar["grad_wire_bytes"]
    return {
        "allreduce": g,
        "reduce_scatter": g // 2,           # the AG half becomes params
        "quantized_int8_block256": int(g // 2
                                       * compressed_size_ratio("int8", 256)),
    }


def main():
    import jax

    dev = jax.devices()[0]
    platform = dev.platform
    on_accel = platform not in ("cpu",)
    peak_tflops, hbm_gbps = _chip_specs(dev) if on_accel else (None, None)

    main_bs = 256 if on_accel else 8
    alt_bs = 128 if on_accel else 4
    iters = 20 if on_accel else 3

    imgs_s, blocked_ms, losses, flops, bytes_acc, _ = _resnet_throughput(
        main_bs, iters)
    alt_imgs_s, _, _, _, _, (alt_exe, alt_loss) = _resnet_throughput(
        alt_bs, iters)
    pf_imgs_s, pf_link_mbps, pf_util = _resnet_prefetcher_throughput(
        alt_bs, iters, alt_exe, alt_loss)
    infer_bs16 = _resnet_infer_throughput(16, 30 if on_accel else 3)
    (served_bs16, served_link_mbps, served_util,
     served_utils) = _resnet_served_throughput(
        16, 32 if on_accel else 4, 8)
    h2d_mbps = _h2d_bandwidth_mbps(alt_bs)
    flash_speedup = _flash_attention_speedup() if on_accel else None

    loss_first, loss_last = losses[0], losses[-1]
    if not loss_last < loss_first:  # not assert: must survive python -O
        raise RuntimeError(
            f"loss did not decrease over the timed window "
            f"({loss_first:.3f} -> {loss_last:.3f}); benchmark invalid")

    implied_tflops = flops * imgs_s / main_bs / 1e12 if flops else None
    # step-time breakdown vs the chip rooflines (round-3 attribution,
    # VERDICT r2 #1): ideal_hbm_ms is XLA's own bytes-accessed estimate at
    # the chip's HBM bandwidth; roofline_fraction ~1.0 means the step IS
    # the memory roofline — on a v5e (197 TFLOP/s : 819 GB/s = 240
    # flops/byte) ResNet-50's arithmetic intensity (~75 flops/byte) makes
    # the HBM roofline, not the MXU, the binding limit. Per-call dispatch
    # measured separately at ~3 ms (scan-fused in-graph loop differs from
    # the host loop by that much; tools/profile_resnet.py).
    step_ms = main_bs / imgs_s * 1e3
    breakdown = None
    if flops and peak_tflops:
        breakdown = {
            "measured_step_ms": round(step_ms, 1),
            "ideal_mxu_ms": round(flops / (peak_tflops * 1e12) * 1e3, 1),
        }
        if bytes_acc and hbm_gbps:
            ideal_hbm = bytes_acc / (hbm_gbps * 1e9) * 1e3
            breakdown["bytes_accessed_xla"] = bytes_acc
            breakdown["ideal_hbm_ms"] = round(ideal_hbm, 1)
            breakdown["hbm_roofline_fraction"] = round(ideal_hbm / step_ms,
                                                       3)
    evidence = {
        "device_kind": getattr(dev, "device_kind", str(dev)),
        "flops_per_step_xla": flops,
        "implied_tflops": round(implied_tflops, 2) if implied_tflops else None,
        "peak_bf16_tflops": peak_tflops,
        "mfu": (round(implied_tflops / peak_tflops, 4)
                if implied_tflops and peak_tflops else None),
        "loss_first": round(loss_first, 4),
        "loss_last": round(loss_last, 4),
        "n_distinct_batches": N_DISTINCT_BATCHES,
        "blocked_step_ms": round(blocked_ms, 1),
        "step_time_breakdown": breakdown,
        f"images_per_sec_bs{alt_bs}": round(alt_imgs_s, 2),
        f"prefetcher_fed_images_per_sec_bs{alt_bs}": round(pf_imgs_s, 2),
        # link measured in the SAME run with the same uint8 wire format and
        # the same 4-stream concurrency (before + after the fed windows,
        # best sample): the utilization is the framework-controlled number;
        # the absolute link drifts ~2-5x between dev-tunnel sessions, which
        # is exactly how round 3's 55 img/s artifact read as 47% of a stale
        # link measure. Values >1.0 mean the sustained pipeline beat the
        # burst probe — the probe is a lower bound on capacity
        "prefetcher_same_run_link_MBps": round(pf_link_mbps, 2),
        "prefetcher_link_utilization": round(pf_util, 3),
        "staged_wire_bytes_per_image": 224 * 224 * 3,
        "fp32_wire_bytes_per_image": 224 * 224 * 3 * 4,
        "infer_images_per_sec_bs16": round(infer_bs16, 2),
        # server-mode (PredictorServer, 8 pipelined requests in flight on
        # one connection): what the serving stack sustains when requests
        # overlap, vs the conservative chained-RTT number above
        "infer_images_per_sec_served_pipelined_bs16": round(served_bs16, 2),
        # serving reconciliation: fraction of the same-run h2d link the
        # served wire rate consumes (>0.7 = the server is transport-bound
        # through the tunnel, not compute- or framework-bound)
        "served_same_run_link_MBps": round(served_link_mbps, 2),
        "served_link_utilization": round(served_util, 3),
        # per-window utilizations + half-spread error bar (VERDICT r5 #4:
        # the r05 artifact committed one point out of a 0.54-0.71 spread)
        "served_link_utilization_runs": [round(u, 3) for u in served_utils],
        "served_link_utilization_error_bar": round(
            (max(served_utils) - min(served_utils)) / 2, 3),
        "infer_vs_reference_best": round(
            infer_bs16 / INFER_BASELINE_IMGS_PER_SEC, 3),
        "infer_reference_best_images_per_sec":
            INFER_BASELINE_IMGS_PER_SEC,
        "h2d_staging_MBps": round(h2d_mbps, 1),
        "flash_attention_fwd_bwd_speedup_vs_xla_T8192": flash_speedup,
        # data-parallel scale-out wire cost of THIS flagship step (ISSUE
        # r8): analytic ring model over the program's trainable params.
        # ResNet's batch_norm keeps it on the SPMD allreduce path (the
        # explicit pipeline rejects batch-global ops), so reduce_scatter/
        # quantized rows are the analytic what-if for this param set; the
        # measured A/B lives in BENCH_DP_r08.json on the BN-free configs.
        "dp8_grad_wire_bytes_per_step": _dp_comm_wire_evidence(),
    }
    print(json.dumps({
        "metric": f"resnet50_train_images_per_sec_bs{main_bs}_{platform}",
        "value": round(imgs_s, 2),
        "unit": "images/sec",
        "vs_baseline": round(imgs_s / BASELINE_IMGS_PER_SEC, 3),
        "evidence": evidence,
    }))


if __name__ == "__main__":
    main()
