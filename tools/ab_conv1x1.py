"""Flagship A/B of the mixed-emitter 1x1 conv backward (PROBE_DGRAD #1).

ResNet-50's bottleneck/projection 1x1 convs are ~2/3 of its conv count;
probe_dgrad.py --exp mixed_1x1 measured the mixed custom_vjp (dot dgrad + conv wgrad) at
1.52x on the worst-traffic 1x1 unit in isolation. This runs the WHOLE
train step (bs256) with the lowering flag on / off / on (ABA bounds
tunnel drift) and reports step time + cost-model traffic for each.

    env PYTHONPATH=/root/.axon_site:/root/repo python tools/ab_conv1x1.py
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from probe_common import measure_step  # noqa: E402


def _measure(flag: bool, iters=10):
    import paddle_tpu as pt
    from paddle_tpu import models
    from paddle_tpu.core import flags as _flags

    _flags._REGISTRY["conv1x1_mixed_vjp"].value = flag
    rng = np.random.RandomState(0)

    def build():
        loss, acc, _ = models.resnet.resnet_imagenet(
            depth=50, is_test=False, data_format="NHWC", use_bf16=True)
        return loss, pt.optimizer.MomentumOptimizer(learning_rate=3e-3,
                                                    momentum=0.9)

    def feed(b=256):
        return {"img": rng.rand(b, 224, 224, 3).astype("float32"),
                "label": rng.randint(0, 1000, (b, 1)).astype("int64")}

    m = measure_step(build, feed, iters=iters)
    rec = {"conv1x1_mixed_vjp": flag,
           "step_ms": round(m["step_s"] * 1e3, 2),
           "bytes_GB": round(m["bytes_acc"] / 1e9, 2),
           "flops_G": round(m["flops"] / 1e9, 1)}
    print(json.dumps(rec), flush=True)
    return rec


def main():
    a1 = _measure(True)
    b = _measure(False)
    a2 = _measure(True)
    best_mixed = min(a1["step_ms"], a2["step_ms"])
    print(json.dumps({
        "exp": "flagship_ab_conv1x1_mixed_vjp",
        "mixed_best_ms": best_mixed,
        "plain_ms": b["step_ms"],
        "speedup": round(b["step_ms"] / best_mixed, 3),
        "bytes_GB": {"mixed": a1["bytes_GB"], "plain": b["bytes_GB"]},
    }), flush=True)


if __name__ == "__main__":
    main()
