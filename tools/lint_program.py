#!/usr/bin/env python
"""Static program linter CLI over framework/analysis.py.

Builds any model from paddle_tpu/models (training nets AND the serving
engine's programs), optionally applies the parallelism rewrite passes
(--tp / --dp / --pipeline_stages), runs the full static analyzer
(structural + parallel + dataflow verification AND whole-program
shape/dtype inference), prints a diagnostics table with block/op#/op.type
provenance, and reports the static peak-live-bytes estimate from variable
lifetimes.

    JAX_PLATFORMS=cpu python tools/lint_program.py --model mnist
    JAX_PLATFORMS=cpu python tools/lint_program.py --model transformer_lm \
        --pipeline_stages 2 --num_microbatches 4
    JAX_PLATFORMS=cpu python tools/lint_program.py --all --json
    JAX_PLATFORMS=cpu python tools/lint_program.py --all --dp 2 --json \
        --allow_gate_rejects

--json emits ONE machine-readable document on stdout (a list of per-model
objects: model, config, ops, diagnostics [{code, severity, loc, message}],
inference/memory summaries, gate_rejected) and nothing else — the CI gate
(tools/run_ci.sh lint-all stanza) consumes it instead of scraping the
table.

Exit status (documented contract, pinned by tests/test_dataflow.py):
  0  every analyzed program is clean (warnings allowed); models whose
     requested config was rejected by a pass gate count as SKIPPED only
     under --allow_gate_rejects
  1  at least one error-severity diagnostic
  2  a pass gate rejected the requested config (tp/dp/pipeline enforce)
     and --allow_gate_rejects was not given — the config does not apply
     to that model, which is itself a lint finding for a hand-picked run
     but expected noise for a sweep
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# builders returning None build an INFERENCE program (no loss to minimize)
# into the default main program — the serving path (engine decode tick,
# prefill/generate) rides these.
def _builders():
    from paddle_tpu import layers, models

    def mt():
        from paddle_tpu.models import machine_translation as m
        src = layers.data("src", shape=[8], dtype="int64")
        src_lens = layers.data("src_lens", shape=[], dtype="int64")
        tgt_in = layers.data("tgt_in", shape=[8], dtype="int64")
        tgt_out = layers.data("tgt_out", shape=[8], dtype="int64")
        tgt_mask = layers.data("tgt_mask", shape=[8], dtype="float32")
        return m.train_net(src, src_lens, tgt_in, tgt_out, tgt_mask,
                           dict_size=1000, embed_dim=64, hidden_dim=64)[0]

    def decode_tick():
        # the continuous-batching engine's compiled step
        # (serving_engine.py builds exactly this shape)
        models.transformer.transformer_lm_decode_tick(
            n_slots=4, vocab=1000, max_len=32, d_model=64, d_inner=128,
            num_heads=4, num_layers=2)
        return None

    def paged_decode_tick():
        # the paged engine's compiled step (serving/kv_pager.py builds
        # exactly this shape: block-table gather + paged_cache_write)
        models.transformer.transformer_lm_paged_decode_tick(
            n_slots=4, n_blocks=17, block_size=8, blocks_per_req=4,
            vocab=1000, d_model=64, d_inner=128, num_heads=4,
            num_layers=2)
        return None

    def quant_decode_tick():
        # the weight-only quantized engine's compiled step: the decode
        # tick rewritten in place by quantize_params_pass (startup runs
        # first so the pass has real weight arrays to quantize)
        import paddle_tpu as pt
        from paddle_tpu.framework.passes import get_pass
        models.transformer.transformer_lm_decode_tick(
            n_slots=4, vocab=1000, max_len=32, d_model=64, d_inner=128,
            num_heads=4, num_layers=2)
        pt.Executor().run(pt.default_startup_program())
        get_pass("quantize_params_pass", bits=8)(
            pt.default_main_program(), pt.global_scope())
        return None

    def draft_tick():
        # the speculative draft model's compiled tick
        # (serving/speculative.py builds exactly this shape: the
        # target's architecture at half depth, weights under the
        # reserved draft_ prefix, logp emitted for rejection sampling)
        models.transformer.transformer_lm_decode_tick(
            n_slots=4, vocab=1000, max_len=32, d_model=64, d_inner=128,
            num_heads=4, num_layers=1, cache_prefix="lintdr",
            param_prefix="draft_", emit_logp=True)
        return None

    def spec_verify_tick():
        # the speculative verify forward: γ+1 window positions scored
        # through ONE target forward against the slot caches
        models.transformer.transformer_lm_spec_verify_tick(
            n_slots=4, gamma=4, vocab=1000, max_len=32, d_model=64,
            d_inner=128, num_heads=4, num_layers=2)
        return None

    def paged_spec_verify_tick():
        # ... and its paged twin: the same window scored through the
        # block-table gather + paged_cache_write path
        models.transformer.transformer_lm_paged_spec_verify_tick(
            n_slots=4, gamma=4, n_blocks=17, block_size=8,
            blocks_per_req=4, vocab=1000, d_model=64, d_inner=128,
            num_heads=4, num_layers=2)
        return None

    def prefill():
        # the teacher-forced prefill + greedy/beam generation program the
        # engine's prompt phase shares weights with
        models.transformer.transformer_lm_generate(
            vocab=1000, max_gen=8, d_model=64, d_inner=128, num_heads=4,
            num_layers=2, beam_size=4)
        return None

    return {
        "mnist": lambda: models.mnist.mlp()[0],
        "mnist_conv": lambda: models.mnist.conv_net()[0],
        "resnet": lambda: models.resnet.resnet_imagenet(depth=50)[0],
        "resnet_cifar10": lambda: models.resnet.resnet_cifar10(depth=20)[0],
        "vgg": lambda: models.vgg.vgg16_cifar()[0],
        "alexnet": lambda: models.alexnet.alexnet_imagenet()[0],
        "googlenet": lambda: models.googlenet.googlenet_imagenet()[0],
        "se_resnext": lambda: models.se_resnext.se_resnext_imagenet(
            depth=50)[0],
        "deepfm": lambda: models.deepfm.deepfm()[0],
        "ssd": lambda: models.ssd.ssd_detector()[0],
        "ocr_crnn": lambda: models.ocr_crnn.crnn_ctc()[0],
        "stacked_lstm": lambda: models.stacked_lstm.stacked_lstm_net(
            dict_dim=10000, emb_dim=128, hid_dim=128)[0],
        "lstm_lm": lambda: models.stacked_lstm.lstm_language_model(
            vocab_size=10000, emb_dim=64, hid_dim=64)[0],
        "transformer_lm": lambda: models.transformer.transformer_lm(
            vocab=1000, max_len=32, d_model=64, d_inner=128, num_heads=4,
            num_layers=2)[0],
        "transformer_lm_tp": _tp_transformer,
        "transformer_lm_decode_tick": decode_tick,
        "transformer_lm_quant_decode_tick": quant_decode_tick,
        "transformer_lm_paged_decode_tick": paged_decode_tick,
        "transformer_lm_draft_tick": draft_tick,
        "transformer_lm_spec_verify_tick": spec_verify_tick,
        "transformer_lm_paged_spec_verify_tick": paged_spec_verify_tick,
        "transformer_lm_prefill": prefill,
        "machine_translation": mt,
    }


def _tp_transformer():
    """tp-annotated transformer_lm: Megatron column/row/vocab shardings
    applied by parallel.auto_shard.annotate_tp; lint with --tp 2 to also
    run the tp_shard_pass rewrite and lint the spliced program."""
    from paddle_tpu import models
    from paddle_tpu.parallel import annotate_tp
    loss, _ = models.transformer.transformer_lm(
        vocab=1000, max_len=32, d_model=64, d_inner=128, num_heads=4,
        num_layers=2, mean_loss=True)
    annotate_tp()
    return loss


def _human(n):
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{n} B"
        n /= 1024.0


def _config_desc(args):
    cfg = {}
    if args.tp >= 2:
        cfg["tp"] = args.tp
    if args.dp >= 2:
        cfg["dp"] = args.dp
    if args.pipeline_stages >= 2:
        cfg["pipeline_stages"] = args.pipeline_stages
        cfg["num_microbatches"] = args.num_microbatches
    if args.memory_plan:
        cfg["memory_plan"] = True
    if getattr(args, "offload", False):
        cfg["offload"] = True
    if args.strategy:
        cfg["strategy"] = args.strategy
    return cfg


_STRATEGY_KEYS = ("dp", "pp", "tp", "microbatches", "schedule", "reduce",
                  "quant", "bucket_bytes", "memory_plan", "offload")


def _parse_strategy(text):
    """--strategy JSON -> a StrategyPoint (auto_parallel's point type).
    Unknown keys raise with the accepted key list."""
    from paddle_tpu.framework.auto_parallel import StrategyPoint
    cfg = json.loads(text)
    bad = sorted(set(cfg) - set(_STRATEGY_KEYS))
    if bad:
        raise SystemExit(
            f"--strategy: unknown key(s) {bad}; accepted keys are "
            f"{list(_STRATEGY_KEYS)}")
    return StrategyPoint(**cfg)


def _apply_strategy(prog, point, args):
    """--strategy: the SAME compile-free feasibility check the
    auto-parallel planner prunes with (costs.strategy_is_feasible) over
    a user-supplied joint config — named rejection reasons statically
    instead of executor enforce raises at run time. Returns
    (program-as-the-executor-would-run-it, feasibility dict,
    gate_reason)."""
    from paddle_tpu.framework import costs as _costs
    feas = _costs.strategy_is_feasible(
        prog, point.to_build_strategy(), mesh_axes=point.mesh_axes(),
        nominal_batch=args.batch_size)
    record = {"point": point.describe(), "ok": feas.ok,
              "reasons": feas.reasons}
    if not feas.ok:
        gate = "; ".join(f"[{r['code']}] {r['message']}"
                         for r in feas.reasons)
        return prog, record, f"strategy infeasible: {gate}"
    return feas.program, record, None


def _apply_config(prog, name, args):
    """tp -> dp -> pipeline, the ParallelExecutor._prepare_program order.
    Returns (program, gate_reason): gate_reason is the enforce text when a
    pass rejected the config (a lint FINDING for a hand-picked run,
    expected noise for a sweep — see the exit-code contract)."""
    from paddle_tpu.core.enforce import EnforceError
    from paddle_tpu.framework import analysis
    from paddle_tpu.framework import sharding as _sharding
    from paddle_tpu.framework.passes import get_pass

    if args.tp >= 2:
        if not _sharding.has_tp_annotations(prog):
            return prog, (f"--tp {args.tp}: model has no tp sharding "
                          f"annotations (only tp-annotated builders, e.g. "
                          f"transformer_lm_tp, take the tp config)")
        try:
            prog = get_pass("tp_shard_pass", tp=args.tp)(prog)
        except (EnforceError, analysis.ProgramAnalysisError) as e:
            return prog, f"tp_shard_pass: {e}"
    if args.dp >= 2:
        from paddle_tpu.parallel.grad_comm import comm_optimize_pass
        cfg = {"shard_update": True, "quant": "", "block": 512,
               "error_feedback": False,
               "bucket_bytes": args.comm_bucket_bytes}
        try:
            prog = comm_optimize_pass(prog, args.dp, cfg)
        except EnforceError as e:
            return prog, f"comm_optimize_pass: {e}"
    if args.pipeline_stages >= 2:
        try:
            prog = get_pass(
                "pipeline_partition_pass",
                num_stages=args.pipeline_stages,
                num_microbatches=args.num_microbatches,
                dp_axis="dp" if args.dp >= 2 else "",
                reduce_dp=False)(prog)
        except EnforceError as e:
            return prog, f"pipeline_partition_pass: {e}"
    if args.memory_plan:
        from paddle_tpu.framework import memory_plan  # noqa: F401  (registers)
        try:
            # a generous budget so lint always analyzes a NON-trivial
            # plan: the budget gates candidates only under the
            # mandated-recompute mode, but keeping it wide here means a
            # future mode flip still lints the fullest plan the search
            # can choose
            prog = get_pass("memory_plan_pass",
                            nominal_batch=args.batch_size,
                            time_budget_s=1.0)(prog)
        except (EnforceError, analysis.ProgramAnalysisError) as e:
            return prog, f"memory_plan_pass: {e}"
    return prog, None


def _restore_diagnostics(prog, args):
    """--restore_dir: statically check that an elastic snapshot restores
    onto THIS program/config (parallel/elastic.py; the run_ci.sh recovery
    stanza's lint half). Emitted as error-severity diagnostics:

      restore-uncommitted     no committed snapshot / integrity failure
      restore-digest-mismatch a file's content digest disagrees with the
                              COMMIT record (silent corruption)
      restore-missing-var     program declares state the snapshot lacks
      restore-shape-mismatch  saved shape != declared shape
      restore-dp-indivisible  a ZeRO-1-sharded var cannot split over --dp
      restore-ef-unmappable   error-feedback state cannot re-map N→M

    verify_program over the (rewritten) program runs as part of the
    normal lint — a clean report therefore means "the restored program's
    sharded-state placement passes verify_program AND the snapshot's
    contents fit it"."""
    from paddle_tpu.core.enforce import EnforceError
    from paddle_tpu.framework.analysis import Diagnostic
    from paddle_tpu.io import _is_persistable, _select_vars
    from paddle_tpu.parallel import elastic
    from paddle_tpu.sharded_checkpoint import ShardedCheckpoint

    diags = []
    try:
        snap = elastic._resolve_snapshot_dir(args.restore_dir)
        elastic.validate_snapshot(snap)
    except elastic.SnapshotDigestError as e:
        return [Diagnostic("restore-digest-mismatch", args.restore_dir,
                           str(e))]
    except EnforceError as e:
        return [Diagnostic("restore-uncommitted", args.restore_dir,
                           str(e))]
    meta = elastic.read_meta(snap)
    ckpt = ShardedCheckpoint(snap)
    saved = ckpt.vars
    dp = args.dp if args.dp >= 2 else int(meta.get("world", {})
                                          .get("dp", 1))
    new_ef = elastic._ef_layout(prog)
    old_ef = meta.get("ef_layout")
    ef_vars = {t["var"] for t in (new_ef or {}).get("transfers", ())}
    if new_ef is not None:
        if old_ef is None:
            diags.append(Diagnostic(
                "restore-ef-unmappable", snap,
                "program carries error-feedback state but the snapshot "
                "recorded no ef_layout"))
        else:
            old_grads = {g for t in old_ef["transfers"]
                         for g in t["grads"]}
            lost = sorted({g for t in new_ef["transfers"]
                           for g in t["grads"]} - old_grads)
            if lost:
                diags.append(Diagnostic(
                    "restore-ef-unmappable", snap,
                    f"no saved residuals for gradient(s) {lost[:4]}"))
    for v in _select_vars(prog, _is_persistable):
        if v.name in ef_vars or getattr(v, "dp_replica_state", False):
            continue  # re-mapped from ef_layout, not restored by name
        entry = saved.get(v.name)
        if entry is None:
            diags.append(Diagnostic(
                "restore-missing-var", snap,
                f"program declares persistable {v.name!r} but the "
                f"snapshot lacks it"))
            continue
        decl = list(v.shape or ())
        if decl and -1 not in decl and list(entry["shape"]) != decl:
            diags.append(Diagnostic(
                "restore-shape-mismatch", snap,
                f"{v.name!r}: saved {entry['shape']} vs declared {decl}"))
            continue
        if getattr(v, "dp_shard_update", False) and dp >= 2:
            if not entry["shape"] or entry["shape"][0] % dp != 0:
                diags.append(Diagnostic(
                    "restore-dp-indivisible", snap,
                    f"ZeRO-1-sharded {v.name!r} dim0 "
                    f"{entry['shape'] and entry['shape'][0]} does not "
                    f"split over dp={dp}"))
    return diags


def _offload_diagnostics(prog, loss, args):
    """--offload: statically check the host-tier transfer schedules
    (framework/offload.py) of the program being linted.

    Train-step programs (loss is not None): walk the block for
    optimizer-state reads/writes and verify the ZeRO-offload round-trip
    (restore at step entry, spill after last access) never reads a var
    before its h2d arrives — `offload-use-before-arrival` BY NAME when
    it would (r13 named-diagnostic discipline; the per-code mutation
    test lives in tests/test_offload.py).

    Serving tick programs (loss is None): build the two-tier prefetch
    schedule for a window of suspended requests through the SHIPPED
    policy helper (`offload.prefetch_issue_tick` — shared code with
    PagedKVEngine, not a copy) and run the same checker, so a policy
    edit that issues prefetches after their read fails lint before it
    ships."""
    from paddle_tpu.framework import offload as _offload
    if loss is not None:
        events = _offload.optimizer_roundtrip_events(prog)
        kind = "optimizer_roundtrip"
    else:
        distance = 2
        reads = {f"resume_t{t}": t for t in range(distance, distance + 4)}
        events = _offload.kv_prefetch_events(reads, distance)
        kind = "kv_prefetch"
    diags = _offload.check_schedule(events)
    return ({"schedule": kind, "events": len(events),
             "violations": len(diags)}, diags)


def _serving_diagnostics(prog, loss, args):
    """--serving: the serving-tier ownership verifier (r24).

    Three static surfaces, all named-diagnostic (r13 discipline; the
    per-code mutation tests live in tests/test_ownership.py):

    1. cache-write aliasing over the program being linted
       (dataflow.cache_write_aliasing): `serving-cache-write-alias` /
       `serving-cache-stale-read` against the executor's donated-state
       contract (builders pass out=pool, so Cache IS Out).
    2. the two-tier prefetch schedule re-checked under speculative
       rollback windows (offload.check_schedule rollback_windows): the
       shipped policy re-issues prefetches AFTER a rollback, so a window
       at the issue tick is clean — a policy edit that lets a transfer
       straddle a rollback is `offload-stale-after-rollback` by name.
    3. the pager-protocol model check (framework/ownership.py): a
       depth-bounded exhaustive exploration of alloc/share/release,
       radix register/evict, CoW fork, speculative rollback and
       spill/reload interleavings over a small pool, verifying every
       lifetime invariant after every transition; any violation joins
       the diagnostics by its ownership code.
    """
    from paddle_tpu.framework import offload as _offload
    from paddle_tpu.framework import ownership as _ownership
    from paddle_tpu.framework.analysis import Diagnostic
    from paddle_tpu.framework.dataflow import cache_write_aliasing

    diags = list(cache_write_aliasing(prog))

    distance = 2
    reads = {f"resume_t{t}": t for t in range(distance, distance + 4)}
    events = _offload.kv_prefetch_events(reads, distance)
    # the shipped contract: any rollback precedes (or lands on) the
    # re-issued prefetch, so windows at the issue tick must be clean
    windows = {ev.var: [ev.issue_tick] for ev in events}
    diags += _offload.check_schedule(events, rollback_windows=windows)

    checker = _ownership.ModelChecker()
    res = checker.run()
    for v in res.violations:
        diags.append(Diagnostic(v["code"], f"model-check:{v['op']}",
                                v["message"]))
    return ({"model_check": {"states_explored": res.states_explored,
                             "transitions": res.transitions,
                             "depth": res.depth,
                             "violations": len(res.violations)},
             "schedule_events": len(events),
             "violations": len(diags)}, diags)


def lint_one(name, build, args):
    """Returns the per-model report dict (the --json row)."""
    import paddle_tpu as pt
    from paddle_tpu.core import unique_name
    from paddle_tpu.framework import analysis
    from paddle_tpu.framework import sharding as _sharding

    pt.reset_default_programs()
    pt.reset_global_scope()
    t0 = time.time()
    with unique_name.guard():
        loss = build()
        if loss is not None:
            if args.optimizer == "sgd":
                pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
            else:
                pt.optimizer.MomentumOptimizer(
                    0.1, momentum=0.9).minimize(loss)
    prog = pt.default_main_program()
    report = {"model": name, "config": _config_desc(args),
              "gate_rejected": None, "errors": 0, "warnings": 0,
              "diagnostics": []}

    strat_cfg = None
    if args.strategy:
        point = _parse_strategy(args.strategy)
        if loss is None and (point.dp > 1 or point.pp > 1 or point.tp > 1
                             or point.explicit or point.memory_plan):
            report["gate_rejected"] = (
                "inference/serving programs lint in the plain config "
                "only (no backward region to rewrite)")
        else:
            prog, strat_cfg, gate = _apply_strategy(prog, point, args)
            report["strategy_feasible"] = strat_cfg
            report["gate_rejected"] = gate
    elif loss is None and (args.tp >= 2 or args.dp >= 2
                           or args.pipeline_stages >= 2):
        report["gate_rejected"] = (
            "inference/serving programs lint in the plain config only "
            "(no backward region to rewrite)")
    else:
        prog, gate = _apply_config(prog, name, args)
        report["gate_rejected"] = gate
    if report["gate_rejected"]:
        return report
    build_s = time.time() - t0

    t1 = time.time()
    res = analysis.infer_program(prog)
    diags = analysis.verify_program(prog) + res.diagnostics
    if args.restore_dir:
        diags += _restore_diagnostics(prog, args)
    shard_res = None
    if args.tp >= 2 or _sharding.has_tp_annotations(prog):
        shard_res = _sharding.propagate_sharding(
            prog, tp_size=args.tp if args.tp >= 2 else None)
        diags += shard_res.diagnostics
    offload_check = None
    if getattr(args, "offload", False):
        offload_check, offload_diags = _offload_diagnostics(prog, loss,
                                                            args)
        diags += offload_diags
    serving_check = None
    if getattr(args, "serving", False):
        serving_check, serving_diags = _serving_diagnostics(prog, loss,
                                                            args)
        diags += serving_diags
    mem = analysis.peak_live_bytes(prog, nominal_batch=args.batch_size)
    plan = None
    if args.memory_plan and getattr(prog, "_memory_plan_applied", False):
        from paddle_tpu.framework.memory_plan import plan_report
        plan = plan_report(prog)
    analyze_s = time.time() - t1

    n_ops = sum(len(b.ops) for b in prog.blocks)
    errors = [d for d in diags if d.severity == "error"]
    warnings = [d for d in diags if d.severity == "warning"]
    report.update({
        "ops": n_ops, "blocks": len(prog.blocks),
        "build_s": round(build_s, 2), "analyze_s": round(analyze_s, 2),
        "inferred": res.n_inferred, "skipped": res.n_skipped,
        "errors": len(errors), "warnings": len(warnings),
        "diagnostics": [{"code": d.code, "severity": d.severity,
                         "loc": d.loc, "message": d.message}
                        for d in errors + warnings],
        "memory": {k: v for k, v in mem.items() if k != "peak_at"},
        "peak_at": mem["peak_at"],
    })
    if plan is not None:
        def _remat_summary(rm):
            return {k: rm.get(k) for k in
                    ("chosen", "segments", "policy", "stash_freed_bytes")}
        # multi-loss programs carry one decision PER region
        # (plan_report: remat=None, remat_regions=[...])
        rms = ([plan["remat"]] if plan.get("remat")
               else plan.get("remat_regions") or [])
        report["memory_plan"] = {
            "predicted_peak_before": plan["predicted_peak_before"],
            "predicted_peak_after": plan["predicted_peak_after"],
            "n_slots": plan["n_slots"],
            "shared_vars": plan["shared_vars"],
            "remat": _remat_summary(rms[0]) if len(rms) == 1 else None,
            "remat_regions": ([_remat_summary(r) for r in rms]
                              if len(rms) > 1 else None),
            "pp_stages": plan.get("pp_stages"),
        }
    if offload_check is not None:
        report["offload"] = offload_check
    if serving_check is not None:
        report["serving"] = serving_check

    if args.json:
        return report

    print(f"\n== {name} ==")
    print(f"  ops={n_ops} blocks={len(prog.blocks)} "
          f"build={build_s:.2f}s analyze={analyze_s:.2f}s")
    if strat_cfg is not None:
        print(f"  strategy: {strat_cfg['point']} FEASIBLE "
              f"(linting the program as the executor would run it)")
    print(f"  inference: {res.n_inferred}/{res.n_ops} ops inferred, "
          f"{res.n_skipped} skipped (waived/unknown inputs)")
    if shard_res is not None:
        sharded = shard_res.sharded_vars()
        n_seed = len(shard_res.seeded)
        n_coll = len(shard_res.actions)
        print(f"  sharding: {n_seed} annotated var(s) propagated to "
              f"{len(sharded)} sharded var(s), {n_coll} op(s) need tp "
              f"collectives")
        rows = []
        for vn in sorted(sharded):
            spec = sharded[vn]
            v = next((b.var(vn) for b in prog.blocks if b.has_var(vn)),
                     None)
            shape = tuple(v.shape) if v is not None and v.shape else None
            local = (_sharding.tp_local_shape(shape, spec, args.tp)
                     if shape and args.tp >= 2 else None)
            rows.append((vn, "[" + ",".join(s or "-" for s in spec) + "]",
                         str(shape), str(local) if local else "-"))
        if rows:
            w0 = max(len(r[0]) for r in rows)
            w1 = max(len(r[1]) for r in rows)
            w2 = max(len(r[2]) for r in rows)
            print(f"    {'VAR':<{w0}}  {'SPEC':<{w1}}  "
                  f"{'DECLARED':<{w2}}  TP-LOCAL")
            for vn, spec, shape, local in rows[:args.max_shard_rows]:
                print(f"    {vn:<{w0}}  {spec:<{w1}}  {shape:<{w2}}  "
                      f"{local}")
            if len(rows) > args.max_shard_rows:
                print(f"    ... {len(rows) - args.max_shard_rows} more")
    if plan is not None:
        rms = ([plan["remat"]] if plan.get("remat")
               else plan.get("remat_regions") or [])
        remat_txt = ", ".join(
            (f"{rm.get('chosen', '-')}"
             + (f" ({rm['segments']} segments, "
                f"policy={rm.get('policy') or 'full'})"
                if rm.get("chosen") == "remat" else ""))
            for rm in rms) or "-"
        print(f"  memory plan (batch={args.batch_size}): predicted peak "
              f"{_human(plan['predicted_peak_before'])} -> "
              f"{_human(plan['predicted_peak_after'])}, "
              f"{plan['n_slots']} shared slot(s) over "
              f"{plan['shared_vars']} var(s), remat={remat_txt}")
        for row in plan["slots"][:args.max_shard_rows]:
            print(f"    slot {row['slot']}: {row['reuses']} reuse(s) of "
                  f"{_human(row['bytes'])}  <- {row['vars']}")
        if len(plan["slots"]) > args.max_shard_rows:
            print(f"    ... {len(plan['slots']) - args.max_shard_rows} "
                  f"more slot(s)")
    sub = mem.get("sub_block_peaks") or {}
    sub_txt = (f" (+{len(sub)} sub-block(s), "
               f"{_human(sum(sub.values()))} at their binders)"
               if sub else "")
    print(f"  memory (batch={args.batch_size}, whole-program lifetimes): "
          f"params+state {_human(mem['persistent_bytes'])}, "
          f"feeds {_human(mem['feed_bytes'])}, "
          f"peak transient {_human(mem['peak_transient_bytes'])} "
          f"at {mem['peak_at']}{sub_txt}")
    if serving_check is not None:
        mc = serving_check["model_check"]
        print(f"  serving verifier: model check explored "
              f"{mc['states_explored']} states / {mc['transitions']} "
              f"transitions at depth {mc['depth']}, "
              f"{mc['violations']} violation(s); "
              f"{serving_check['schedule_events']} schedule event(s) "
              f"rollback-checked")
    if not diags:
        print("  diagnostics: clean")
    else:
        print(f"  diagnostics: {len(errors)} error(s), "
              f"{len(warnings)} warning(s)")
        rows = [(d.severity.upper(), d.code, d.loc, d.message)
                for d in errors + warnings]
        w0 = max(len(r[0]) for r in rows)
        w1 = max(len(r[1]) for r in rows)
        w2 = max(len(r[2]) for r in rows)
        for sev, code, loc, msg in rows[:args.max_diags]:
            print(f"    {sev:<{w0}}  {code:<{w1}}  {loc:<{w2}}  {msg}")
        if len(rows) > args.max_diags:
            print(f"    ... {len(rows) - args.max_diags} more")
    return report


def main():
    builders = _builders()
    p = argparse.ArgumentParser(
        description="static analyzer CLI (shape/dtype inference + "
                    "structural/parallel/dataflow verification + memory "
                    "estimate)")
    p.add_argument("--model", choices=sorted(builders), default="mnist")
    p.add_argument("--all", action="store_true",
                   help="lint every model builder")
    p.add_argument("--json", action="store_true",
                   help="emit one JSON list of per-model reports on "
                        "stdout and nothing else (the run_ci.sh lint-all "
                        "contract)")
    p.add_argument("--allow_gate_rejects", action="store_true",
                   help="a pass gate rejecting the requested config "
                        "counts as a skip (exit 0), not exit 2 — for "
                        "sweeps over builders x configs")
    p.add_argument("--batch_size", type=int, default=8,
                   help="stand-in for the symbolic batch dim in the "
                        "memory estimate")
    p.add_argument("--optimizer", choices=("sgd", "momentum"),
                   default="sgd")
    p.add_argument("--pipeline_stages", type=int, default=0,
                   help="apply pipeline_partition_pass and lint the "
                        "partitioned program")
    p.add_argument("--num_microbatches", type=int, default=4)
    p.add_argument("--dp", type=int, default=0,
                   help="data-parallel degree: apply the explicit "
                        "reduce-scatter gradient pipeline "
                        "(grad_comm.comm_optimize_pass) and lint the "
                        "rewritten program")
    p.add_argument("--comm_bucket_bytes", type=int, default=1 << 20)
    p.add_argument("--memory_plan", action="store_true",
                   help="apply the static memory planner "
                        "(framework/memory_plan.py memory_plan_pass) "
                        "after the parallelism rewrites and lint the "
                        "PLANNED program: prints the buffer-slot table "
                        "and the predicted peak before/after; any "
                        "error-severity diagnostic the plan introduces "
                        "(the r13 buffer-reuse detectors) exits 1")
    p.add_argument("--offload", action="store_true",
                   help="check the host-tier transfer schedules "
                        "(framework/offload.py): the ZeRO-offload "
                        "optimizer round-trip for train-step programs, "
                        "the two-tier KV prefetch policy for serving "
                        "ticks — a transfer arriving after its first "
                        "read is the error-severity "
                        "offload-use-before-arrival diagnostic")
    p.add_argument("--serving", action="store_true",
                   help="serving-tier ownership verifier: cache-write "
                        "aliasing over the linted program "
                        "(serving-cache-write-alias / "
                        "serving-cache-stale-read), the prefetch "
                        "schedule under speculative rollback windows "
                        "(offload-stale-after-rollback), and the "
                        "exhaustive small-scope model check of the "
                        "pager protocol (framework/ownership.py) — the "
                        "state count lands in the --json report, any "
                        "violation exits 1 under its ownership code")
    p.add_argument("--tp", type=int, default=0,
                   help="tensor-parallel degree: apply tp_shard_pass to a "
                        "tp-annotated program (e.g. --model "
                        "transformer_lm_tp) and lint the spliced program; "
                        "the propagated sharding-spec table prints per "
                        "sharded var")
    p.add_argument("--strategy", default="",
                   help="JSON joint-strategy config, e.g. "
                        "'{\"dp\": 2, \"pp\": 2, \"microbatches\": 4, "
                        "\"reduce\": \"reduce_scatter\"}' (keys: dp, pp, "
                        "tp, microbatches, schedule, reduce, quant, "
                        "bucket_bytes, memory_plan): run the SAME "
                        "compile-free feasibility check the auto-parallel "
                        "planner prunes with (costs.strategy_is_feasible) "
                        "and lint the rewritten program when feasible; an "
                        "infeasible config reports its NAMED rejection "
                        "reasons and exits 2 (the gate-reject contract). "
                        "Mutually exclusive with --dp/--tp/"
                        "--pipeline_stages/--memory_plan")
    p.add_argument("--restore_dir", default="",
                   help="elastic snapshot dir (or root of snapshot-* "
                        "dirs, parallel/elastic.py): statically verify "
                        "the snapshot restores onto this model/config — "
                        "commit integrity, every declared persistable "
                        "present at its declared shape, ZeRO-1 dim0 "
                        "divisibility at --dp, error-feedback "
                        "re-mappability (the run_ci.sh recovery stanza)")
    p.add_argument("--max_shard_rows", type=int, default=24)
    p.add_argument("--max_diags", type=int, default=40)
    args = p.parse_args()
    if args.strategy and (args.dp >= 2 or args.tp >= 2
                          or args.pipeline_stages >= 2
                          or args.memory_plan):
        p.error("--strategy carries the whole joint config; do not "
                "combine it with --dp/--tp/--pipeline_stages/"
                "--memory_plan")

    names = sorted(builders) if args.all else [args.model]
    reports = [lint_one(name, builders[name], args) for name in names]
    n_errors = sum(r["errors"] for r in reports)
    gates = [r for r in reports if r["gate_rejected"]]
    if args.json:
        print(json.dumps(reports, indent=1))
    else:
        for r in gates:
            print(f"\n== {r['model']} ==\n  GATE REJECTED  "
                  f"{r['gate_rejected']}")
        print(f"\nlint: {len(names)} program(s), {n_errors} error(s), "
              f"{len(gates)} gate-rejected")
    if n_errors:
        sys.exit(1)
    if gates and not args.allow_gate_rejects:
        sys.exit(2)
    sys.exit(0)


if __name__ == "__main__":
    main()
