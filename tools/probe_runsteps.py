"""Probe: does the scan-fused run_steps loop beat the host-loop throughput
on the flagship? (Amortizes the tunnel's ~3 ms/step dispatch; on a real TPU
host it removes the per-step Python round trip.)

    env PYTHONPATH=/root/.axon_site:/root/repo python tools/probe_runsteps.py
"""
import json
import sys
import time

import numpy as np


def main(batch=256, k=10, windows=3):
    import jax.numpy as jnp

    sys.path.insert(0, "/root/repo")
    import bench

    exe, loss = bench._build_resnet_train(batch)
    rng = np.random.RandomState(0)
    feed = {
        "img": jnp.asarray(rng.rand(batch, 224, 224, 3).astype("float32")),
        "label": jnp.asarray(
            rng.randint(0, 1000, (batch, 1)).astype("int64")),
    }
    feed_list = [feed] * k

    # host loop reference
    out = exe.run(feed=feed, fetch_list=[loss], return_numpy=False)
    float(out[0])

    def host_window():
        t0 = time.time()
        fetched = []
        for _ in range(k):
            o = exe.run(feed=feed, fetch_list=[loss], return_numpy=False)
            fetched.append(o[0])
        float(fetched[-1])
        return (time.time() - t0) / k

    out = exe.run_steps(feed_list, fetch_list=[loss], return_numpy=False)
    float(np.asarray(out[0])[-1])  # compile + drain

    def scan_window():
        t0 = time.time()
        o = exe.run_steps(feed_list, fetch_list=[loss], return_numpy=False)
        float(np.asarray(o[0])[-1])
        return (time.time() - t0) / k

    best = {"host": None, "scan": None}
    for _ in range(windows):
        for name, fn in (("host", host_window), ("scan", scan_window)):
            dt = fn()
            best[name] = dt if best[name] is None else min(best[name], dt)
    print(json.dumps({
        "host_step_ms": round(best["host"] * 1e3, 1),
        "scan_step_ms": round(best["scan"] * 1e3, 1),
        "host_imgs_s": round(batch / best["host"], 1),
        "scan_imgs_s": round(batch / best["scan"], 1),
    }))


if __name__ == "__main__":
    main()
