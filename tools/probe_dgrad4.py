"""Mixed-emitter 1x1 conv backward: the decisive dgrad experiment.

probe_dgrad2 (interleaved per-dispatch A/B — absolute times carry tunnel
dispatch overhead but it lands symmetrically on both sides) showed:
  - ISOLATED 1x1 dgrad: the dot_general formulation beats the conv
    emitter 1.33x and reads fewer cost-model bytes (1189 vs 1541 MB —
    the conv emitter pads 64 channels to 128 lanes);
  - the full vjp (fwd+dgrad+wgrad): all-conv beats all-dot 1.24x, because
    the wgrad-as-matmul is a [Ci, B*H*W] x [B*H*W, Co] huge-K skinny
    GEMM the matmul emitter handles worse than the conv emitter.

So the open question is the MIXED split: conv fwd + dot dgrad + conv
wgrad via custom_vjp — each half routed to the emitter that won its
isolated probe. This file measures exactly that pair, interleaved.

    env PYTHONPATH=/root/.axon_site:/root/repo python tools/probe_dgrad4.py
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

DN = ("NHWC", "HWIO", "NHWC")
B, HW, Ci, Co = 256, 56, 256, 64


def conv_fwd(x, w):
    return jax.lax.conv_general_dilated(
        x, w, window_strides=(1, 1), padding="SAME", dimension_numbers=DN)


@jax.custom_vjp
def conv1x1_mixed(x, w):
    return conv_fwd(x, w)


def _mixed_fwd(x, w):
    return conv_fwd(x, w), (x, w)


def _mixed_bwd(res, dy):
    x, w = res
    # dgrad as one dot_general (a 1x1 conv IS a matmul)
    dy2 = dy.reshape(-1, Co)
    dx = jax.lax.dot_general(
        dy2, w.reshape(Ci, Co), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dy.dtype)
    dx = dx.reshape(B, HW, HW, Ci)
    # wgrad through the conv emitter (its win in probe_dgrad2)
    _, vjp = jax.vjp(lambda w_: conv_fwd(x, w_), w)
    dw = vjp(dy)[0]
    return dx, dw


conv1x1_mixed.defvjp(_mixed_fwd, _mixed_bwd)


def _make_runner(fn, x, w, dy, reps=20):
    def loss(x, w):
        return jnp.sum(fn(x, w).astype(jnp.float32) * dy)

    g = jax.jit(jax.value_and_grad(loss, argnums=(0, 1)))
    out = g(x, w)
    float(np.asarray(out[1][0][(0,) * 4]))   # compile + drain

    def run():
        t0 = time.time()
        o = None
        for _ in range(reps):
            o = g(x, w)
        float(np.asarray(o[1][0][(0,) * 4]))  # trusted barrier
        return (time.time() - t0) / reps
    return run


def main():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(B, HW, HW, Ci).astype("float32"), jnp.bfloat16)
    w = jnp.asarray(rng.rand(1, 1, Ci, Co).astype("float32"), jnp.bfloat16)
    dy = jnp.asarray(rng.rand(B, HW, HW, Co).astype("float32"),
                     jnp.float32)

    run_conv = _make_runner(lambda x, w: conv_fwd(x, w), x, w, dy)
    run_mixed = _make_runner(conv1x1_mixed, x, w, dy)

    # parity first
    g1 = jax.grad(lambda x_: jnp.sum(conv_fwd(x_, w).astype(jnp.float32)
                                     * dy))(x)
    g2 = jax.grad(lambda x_: jnp.sum(conv1x1_mixed(x_, w)
                                     .astype(jnp.float32) * dy))(x)
    np.testing.assert_allclose(np.asarray(g1, np.float32),
                               np.asarray(g2, np.float32),
                               rtol=2e-2, atol=2e-1)

    best = {"vjp_conv": None, "vjp_mixed": None}
    for _ in range(4):
        for name, run in (("vjp_conv", run_conv), ("vjp_mixed", run_mixed)):
            dt = run()
            best[name] = dt if best[name] is None else min(best[name], dt)
    ratio = best["vjp_conv"] / best["vjp_mixed"]
    print(json.dumps({
        "exp": "mixed_emitter_1x1_vjp",
        "vjp_conv_ms": round(best["vjp_conv"] * 1e3, 3),
        "vjp_mixed_ms": round(best["vjp_mixed"] * 1e3, 3),
        "mixed_speedup_over_conv": round(ratio, 3),
        "note": "interleaved per-dispatch best-of-4; dispatch overhead "
                "symmetric on both sides",
    }), flush=True)


if __name__ == "__main__":
    main()
