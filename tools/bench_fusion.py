#!/usr/bin/env python
"""A/B benchmark for the fusion subsystem (paddle_tpu/fusion/).

Measures the two small-step hot paths VERDICT r5 flagged, fused vs
unfused, through the REAL benches (not isolated kernels — the
conv1x1-mixed probe showed isolated wins can lose in situ):

  - stacked-LSTM train step (the `tools/benchmark.py --model stacked_lstm`
    graph): per-step and per-tick latency with fuse_recurrent_cells
    off/on.
  - KV-cached LM decode (the `tools/bench_generate.py` graph): ms per
    decode tick at bs16/bs64 greedy + bs16 beam-4 with
    fuse_decode_attention off/on.

    env PYTHONPATH=/root/repo python tools/bench_fusion.py \
        | tee BENCH_FUSION_r06.json

On a non-accelerator host the shapes shrink (same policy as
bench_generate) — numbers are then CPU-mesh evidence of graph-level
overhead only; the kernel-level win needs TPU.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _best_of(fn, iters, windows=3):
    best = None
    for _ in range(windows):
        t0 = time.time()
        for _ in range(iters):
            out = fn()
        np.asarray(out)  # host realization is the only trusted barrier
        dt = (time.time() - t0) / iters
        best = dt if best is None else min(best, dt)
    return best


def measure_stacked_lstm(fuse: bool, batch, seq, hid, iters):
    import paddle_tpu as pt
    from paddle_tpu import models
    from paddle_tpu.core import flags, unique_name

    pt.reset_default_programs()
    pt.reset_global_scope()
    flags.set_flag("fuse_recurrent_cells", fuse)
    with unique_name.guard():
        loss, acc, _ = models.stacked_lstm.stacked_lstm_net(
            dict_dim=10000, emb_dim=hid, hid_dim=hid, max_len=seq)
        pt.optimizer.MomentumOptimizer(
            learning_rate=0.01, momentum=0.9).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.RandomState(0)
    feed = {"words": rng.randint(0, 10000, (batch, seq)).astype("int64"),
            "words@SEQLEN": np.full((batch,), seq, "int32"),
            "label": rng.randint(0, 2, (batch, 1)).astype("int64")}
    run = lambda: exe.run(feed=feed, fetch_list=[loss])[0]  # noqa: E731
    run()  # compile + drain
    return _best_of(run, iters)


def measure_decode(fuse: bool, batch, gen_len, beam, iters, vocab=32000,
                   d_model=512, d_inner=2048, num_heads=8, num_layers=6):
    import paddle_tpu as pt
    from paddle_tpu.core import flags, unique_name
    from paddle_tpu.models import transformer

    pt.reset_default_programs()
    pt.reset_global_scope()
    flags.set_flag("fuse_decode_attention", fuse)
    with unique_name.guard():
        seqs, _ = transformer.transformer_lm_generate(
            vocab=vocab, max_gen=gen_len, d_model=d_model, d_inner=d_inner,
            num_heads=num_heads, num_layers=num_layers, bos_id=1,
            beam_size=beam)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    feed = {"prompt": np.full((batch, 1), 1, "int64")}
    run = lambda: exe.run(feed=feed, fetch_list=[seqs])[0]  # noqa: E731
    out = run()
    assert np.asarray(out).shape == (batch, gen_len, beam)
    return _best_of(run, iters)


def _decode_small(fuse: bool, batch, gen_len, beam, iters):
    """CPU smoke shape of measure_decode (one driver, small dims)."""
    return measure_decode(fuse, batch, gen_len, beam, iters, vocab=2000,
                          d_model=64, d_inner=128, num_heads=2,
                          num_layers=2)


def ab(label, f, trials=1, **kw):
    """A/B with `trials` independent repeats: on a noisy host (the 2-core
    CPU box) a single A/B is not decision-grade — the committed record
    carries the spread, not one draw."""
    pairs = [(f(False, **kw), f(True, **kw)) for _ in range(trials)]
    base = min(b for b, _ in pairs)
    fused = min(fu for _, fu in pairs)
    speedups = sorted(b / fu for b, fu in pairs)
    return {"config": label,
            "unfused_ms": round(base * 1e3, 2),
            "fused_ms": round(fused * 1e3, 2),
            "speedup": round(base / fused, 3),
            "speedup_per_trial": [round(s, 2) for s in speedups]}


def main():
    import jax
    dev = jax.devices()[0]
    on_accel = dev.platform != "cpu"
    results = []

    if on_accel:
        r = ab("stacked_lstm_train_bs32_t64_h256", measure_stacked_lstm,
               batch=32, seq=64, hid=256, iters=10)
        r["per_tick_us"] = {k: round(v * 1e3 / 64, 1)
                           for k, v in (("unfused", r["unfused_ms"]),
                                        ("fused", r["fused_ms"]))}
        results.append(r)
        for batch, beam in ((16, 1), (64, 1), (16, 4)):
            r = ab(f"lm6l_512d_bs{batch}_gen64_beam{beam}", measure_decode,
                   batch=batch, gen_len=64, beam=beam, iters=3)
            r["ms_per_tick"] = {"unfused": round(r["unfused_ms"] / 64, 3),
                               "fused": round(r["fused_ms"] / 64, 3)}
            results.append(r)
    else:
        # CPU smoke shapes: graph-level A/B only (kernel win needs TPU)
        r = ab("stacked_lstm_train_bs8_t16_h128_cpu", measure_stacked_lstm,
               trials=3, batch=8, seq=16, hid=128, iters=5)
        r["per_tick_us"] = {"unfused": round(r["unfused_ms"] * 1e3 / 16, 1),
                           "fused": round(r["fused_ms"] * 1e3 / 16, 1)}
        results.append(r)
        r = ab("lm2l_64d_bs4_gen8_beam1_cpu", _decode_small, trials=3,
               batch=4, gen_len=8, beam=1, iters=3)
        results.append(r)
        r = ab("lm2l_64d_bs4_gen8_beam4_cpu", _decode_small, trials=3,
               batch=4, gen_len=8, beam=4, iters=3)
        results.append(r)

    rec = {
        "bench": "fusion_ab", "round": 6,
        "device_kind": getattr(dev, "device_kind", str(dev)),
        "flags": {"fuse_recurrent_cells": "A/B", "fuse_decode_attention":
                  "A/B"},
        "results": results,
    }
    if not on_accel:
        rec["notes"] = (
            "CPU-mesh A/B: best-of-mins within noise on every config "
            "(see speedup_per_trial spreads) — on CPU both sides lower "
            "to the same XLA composite, so this measures graph-rewrite "
            "overhead only, and it is ~zero. The kernel-level claim "
            "(one Pallas launch per recurrence / per decode tick vs the "
            "per-tick dispatch floor) is a TPU claim, pinned here by "
            "interpret-mode parity tests (tests/test_fusion.py) and "
            "still to be measured on hardware. Flags stay default-ON: "
            "numerics are exact (tier-1-guarded), CPU cost is nil, and "
            "PTPU_FUSE_*=0 is the kill switch.")
    print(json.dumps(rec, indent=1), flush=True)


if __name__ == "__main__":
    main()
