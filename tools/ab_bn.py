"""A/B: legacy (autodiff-through-stats) BN vs the round-4 custom-VJP BN.

Builds the ResNet-50 bs256 train step twice — once with the legacy
batch_norm lowering monkeypatched in, once with the current one — and
reports, for each: XLA cost-analysis bytes/flops, materialized entry-buffer
census (by dtype), and interleaved best-of-N step timing (the only fair
timing through the drifting dev tunnel — see bench.interleaved_best).

    env PYTHONPATH=/root/.axon_site:/root/repo python tools/ab_bn.py
"""

from __future__ import annotations

import json
import re
import time

import numpy as np


def legacy_batch_norm(ctx, ins, attrs):
    """Round-3 final _batch_norm: fma apply, but stats differentiated by
    autodiff (the path whose fp32 residuals VERDICT r3 #1 flagged)."""
    import jax
    import jax.numpy as jnp

    x = ins["X"][0]
    scale, bias = ins["Scale"][0], ins["Bias"][0]
    mean, var = ins["Mean"][0], ins["Variance"][0]
    eps = attrs.get("epsilon", 1e-5)
    momentum = attrs.get("momentum", 0.9)
    data_layout = attrs.get("data_layout", "NCHW")
    is_test = attrs.get("is_test", False) or ctx.is_test
    axis = 1 if data_layout == "NCHW" else x.ndim - 1
    reduce_axes = tuple(i for i in range(x.ndim) if i != axis)
    bshape = [1] * x.ndim
    bshape[axis] = x.shape[axis]
    if is_test:
        use_mean, use_var = mean, var
        mean_out, var_out = mean, var
    else:
        shift_v = jax.lax.stop_gradient(mean)
        x32 = x.astype(jnp.float32) if x.dtype != jnp.float32 else x
        xs_ = x32 - shift_v.reshape(bshape)
        m1s = jnp.mean(xs_, axis=reduce_axes)
        m2s = jnp.mean(jnp.square(xs_), axis=reduce_axes)
        use_mean = m1s + shift_v
        use_var = jnp.maximum(m2s - jnp.square(m1s), 0.0)
        m_d = jax.lax.stop_gradient(use_mean)
        v_d = jax.lax.stop_gradient(use_var)
        mean_out = momentum * mean + (1 - momentum) * m_d
        var_out = momentum * var + (1 - momentum) * v_d
    inv = jax.lax.rsqrt(use_var + eps)
    a32 = inv * scale
    b32 = bias - use_mean * a32
    y = x * a32.astype(x.dtype).reshape(bshape) \
        + b32.astype(x.dtype).reshape(bshape)
    return {"Y": [y], "MeanOut": [mean_out], "VarianceOut": [var_out],
            "SavedMean": [use_mean], "SavedVariance": [inv]}


def build(batch=256):
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu import models

    pt.reset_default_programs()
    pt.reset_global_scope()
    loss, acc, _ = models.resnet.resnet_imagenet(
        depth=50, is_test=False, data_format="NHWC", use_bf16=True)
    opt = pt.optimizer.MomentumOptimizer(learning_rate=3e-3, momentum=0.9)
    opt.minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.RandomState(0)
    feed = {
        "img": jnp.asarray(rng.rand(batch, 224, 224, 3).astype("float32")),
        "label": jnp.asarray(rng.randint(0, 1000, (batch, 1)).astype("int64")),
    }
    return exe, loss, feed


def census(hlo):
    it = {"bf16": 2, "f16": 2, "f32": 4, "s32": 4, "u32": 4, "s8": 1,
          "u8": 1, "pred": 1, "s64": 8, "u64": 8}
    cur = None
    out = {"bf16": 0, "f32": 0}
    for line in hlo.splitlines():
        mc = re.match(r"(ENTRY )?%?([\w.\-]+)\s*\([^)]*\)\s*->", line)
        if mc:
            cur = "ENTRY" if mc.group(1) else mc.group(2)
            continue
        if cur != "ENTRY":
            continue
        m = re.match(r"\s+%?[\w.\-]+\s*=\s*(bf16|f32)\[([0-9,]*)\]", line)
        if not m or "get-tuple-element" in line or "parameter" in line \
                or "bitcast" in line:
            continue
        n = 1
        for d in m.group(2).split(","):
            if d:
                n *= int(d)
        out[m.group(1)] += n * it[m.group(1)]
    return {k: round(v / 1e9, 2) for k, v in out.items()}


def prepare(tag, batch=256, iters=10):
    import paddle_tpu as pt

    exe, loss, feed = build(batch)
    # capture program+scope: the NEXT prepare() resets the global defaults,
    # so the timing closures must not re-resolve them
    prog = pt.default_main_program()
    scope = pt.global_scope()
    compiled = exe._lookup_or_compile(prog, feed, [loss.name], scope)
    import jax.numpy as jnp
    feed_vals = tuple(jnp.asarray(feed[n]) for n in compiled.feed_names)
    scope = pt.global_scope()
    ro_vals = tuple(scope.get(n) for n in compiled.ro_names)
    rw_vals = tuple(scope.get(n) for n in compiled.rw_names)
    ex = compiled.fn.lower(feed_vals, ro_vals, rw_vals,
                           np.uint32(0)).compile()
    ca = ex.cost_analysis()
    ca = ca[0] if isinstance(ca, (list, tuple)) else ca
    stat = {
        "bytes_accessed_GB": round(float(ca.get("bytes accessed", 0)) / 1e9,
                                   2),
        "flops_G": round(float(ca.get("flops", 0)) / 1e9, 1),
        "entry_buffers_GB": census(ex.as_text()),
    }

    out = exe.run(program=prog, feed=feed, fetch_list=[loss],
                  scope=scope, return_numpy=False)
    float(out[0])

    def run():
        t0 = time.time()
        fetched = []
        for _ in range(iters):
            o = exe.run(program=prog, feed=feed, fetch_list=[loss],
                        scope=scope, return_numpy=False)
            fetched.append(o[0])
        float(fetched[-1])
        return (time.time() - t0) / iters

    print(json.dumps({"tag": tag, **stat}), flush=True)
    return run


def main():
    from paddle_tpu.framework import registry
    from paddle_tpu.ops import nn_ops

    run_new = prepare("new_custom_vjp")
    saved = registry._OPS["batch_norm"]
    registry._OPS["batch_norm"] = registry.OpDef(
        "batch_norm", legacy_batch_norm)
    try:
        run_legacy = prepare("legacy_autodiff_stats")
    finally:
        registry._OPS["batch_norm"] = saved

    best = {"new": None, "legacy": None}
    for _ in range(3):
        for name, run in (("new", run_new), ("legacy", run_legacy)):
            dt = run()
            best[name] = dt if best[name] is None else min(best[name], dt)
    print(json.dumps({
        "step_ms_new": round(best["new"] * 1e3, 1),
        "step_ms_legacy": round(best["legacy"] * 1e3, 1),
        "speedup_new_over_legacy": round(best["legacy"] / best["new"], 3),
    }), flush=True)


if __name__ == "__main__":
    main()
