"""Serving engine A/B: continuous vs static batching under Poisson
arrivals, plus a transport link-utilization census.

Two claims, both CPU-mesh-measurable (the ISSUE r7 acceptance bar):

1. SCHEDULING — under a Poisson arrival trace with mixed generation
   lengths, continuous batching (admit the tick a slot frees) sustains
   >= 1.5x the tokens/s of static batching (form a full batch, run it to
   complete drain) at an equal-or-better p95 latency SLO. Both sides run
   the IDENTICAL compiled tick program and transport; only the admission
   policy differs (`ContinuousBatchingEngine(policy=...)`), so the ratio
   isolates the scheduler. >= 3 runs per side, spreads committed.

2. TRANSPORT — the serving.py v2 framing (vectored sendmsg, batched
   response writes, double-buffered recv) against the raw socket: an
   echo predictor is served pipelined and its sustained wire rate is
   divided by a same-run raw-socket streaming probe over an identical
   loopback connection. This is the serving-side analogue of the
   prefetcher's link-utilization discipline (bench.py
   `_link_reconciliation`) with the device removed, so what it prices is
   exactly the per-request protocol turnaround the round-5 artifact
   couldn't attribute (VERDICT r5 weak #3). Target >= 0.85.

    JAX_PLATFORMS=cpu python tools/bench_serve.py | tee BENCH_SERVE_r07.json
"""

from __future__ import annotations

import json
import socket
import threading
import time

import numpy as np

# small LM the 2-core CPU mesh can tick in ~1 ms: the A/B is about the
# scheduler, so the model only needs to be real enough to have a KV cache
_DIMS = dict(vocab=1000, max_len=48, d_model=64, d_inner=128,
             num_heads=4, num_layers=2)
_N_SLOTS = 8

_PAYLOAD = 4 << 20          # 4 MiB per request: per-BYTE costs dominate
#                             per-request costs (measured flat 1->8 MiB)


def _poisson_trace(rng, n_requests, mean_interarrival_s):
    """(arrival_offset_s, prompt, max_new) per request. Generation
    lengths are bimodal (short interactive + long tail) — the mixture
    static batching pays for: every batch runs to its LONGEST member."""
    arrivals = np.cumsum(rng.exponential(mean_interarrival_s, n_requests))
    reqs = []
    for i in range(n_requests):
        plen = int(rng.randint(1, 5))
        prompt = rng.randint(0, _DIMS["vocab"], plen).tolist()
        max_new = int(rng.choice([4, 6, 8, 24, 32],
                                 p=[0.3, 0.25, 0.25, 0.1, 0.1]))
        reqs.append((float(arrivals[i]), prompt, max_new))
    return reqs


def _run_trace(policy, trace, scope):
    """Replay one arrival trace against a fresh engine with `policy`;
    returns (tokens_per_sec, p95_latency_s, occupancy, makespan_s).

    Arrivals are replayed on a real clock by a feeder thread while the
    engine thread ticks — the engine sees requests the moment they
    'arrive', exactly like the server's reader thread would inject
    them."""
    import paddle_tpu as pt
    from paddle_tpu.serving_engine import ContinuousBatchingEngine

    eng = ContinuousBatchingEngine(n_slots=_N_SLOTS, policy=policy,
                                   scope=scope, **_DIMS)
    # warm the compile before the clock starts
    w = eng.submit([1], max_new=1)
    eng.run_until_idle()
    assert w.done
    eng.n_ticks = eng.busy_slot_ticks = eng.total_slot_ticks = 0
    eng.tokens_out = 0

    reqs = []
    t0 = time.time()

    def feeder():
        for off, prompt, max_new in trace:
            delay = t0 + off - time.time()
            if delay > 0:
                time.sleep(delay)
            reqs.append(eng.submit(prompt, max_new))

    f = threading.Thread(target=feeder)
    f.start()
    done = []
    while f.is_alive() or eng.n_active or eng.n_pending:
        out = eng.run_until_idle(max_ticks=64)
        done.extend(out)
        if not out and not (eng.n_active or eng.n_pending):
            time.sleep(0.001)
    f.join()
    makespan = time.time() - t0
    total_tokens = sum(len(r.tokens) for r in done)
    lats = sorted(r.latency_s for r in done)
    p95 = lats[int(np.ceil(0.95 * len(lats))) - 1]
    return (total_tokens / makespan, p95, eng.occupancy(), makespan)


def bench_scheduling(n_runs=3, n_requests=64, mean_interarrival_s=0.0008):
    import paddle_tpu as pt

    pt.reset_default_programs()
    pt.reset_global_scope()
    scope = pt.global_scope()     # both engines share one weight set
    rng = np.random.RandomState(7)
    rows = {"continuous": [], "static": []}
    for run in range(n_runs):
        trace = _poisson_trace(rng, n_requests, mean_interarrival_s)
        # interleave policies within a run (same discipline as
        # bench.interleaved_best): ambient load drift hits both sides
        for policy in ("continuous", "static"):
            tps, p95, occ, mk = _run_trace(policy, trace, scope)
            rows[policy].append({"tokens_per_sec": round(tps, 1),
                                 "p95_latency_ms": round(p95 * 1e3, 1),
                                 "occupancy": round(occ, 3),
                                 "makespan_s": round(mk, 3)})
    out = {"exp": "continuous_vs_static_poisson",
           "n_slots": _N_SLOTS, "model": _DIMS,
           "n_requests_per_run": n_requests,
           "mean_interarrival_ms": mean_interarrival_s * 1e3,
           "gen_len_mix": "{4:.3, 6:.25, 8:.25, 24:.1, 32:.1}",
           "runs": rows}
    for policy in rows:
        tps = [r["tokens_per_sec"] for r in rows[policy]]
        p95 = [r["p95_latency_ms"] for r in rows[policy]]
        out[f"{policy}_tokens_per_sec"] = round(float(np.mean(tps)), 1)
        out[f"{policy}_tokens_per_sec_spread"] = [min(tps), max(tps)]
        out[f"{policy}_p95_ms"] = round(float(np.mean(p95)), 1)
    out["speedup_continuous_over_static"] = round(
        out["continuous_tokens_per_sec"] / out["static_tokens_per_sec"], 3)
    out["equal_slo"] = bool(out["continuous_p95_ms"]
                            <= out["static_p95_ms"])
    print(json.dumps(out), flush=True)
    return out


# ---------------------------------------------------------------------------
# transport census
# ---------------------------------------------------------------------------


def _raw_link_mbps(host, port_holder, total_bytes=64 << 20):
    """Raw loopback streaming rate: one connection, sender blasts
    `total_bytes`, receiver drains — the link capacity the serving
    framing is measured against (same-run, same socket family)."""
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind((host, 0))
    srv.listen(1)
    addr = srv.getsockname()
    got = []

    def drain():
        conn, _ = srv.accept()
        n = 0
        buf = bytearray(1 << 20)
        while True:
            r = conn.recv_into(buf)
            if not r:
                break
            n += r
        got.append(n)
        conn.close()

    t = threading.Thread(target=drain)
    t.start()
    cl = socket.create_connection(addr)
    chunk = b"\x00" * (1 << 20)
    t0 = time.time()
    sent = 0
    while sent < total_bytes:
        cl.sendall(chunk)
        sent += len(chunk)
    cl.shutdown(socket.SHUT_WR)
    t.join()
    dt = time.time() - t0
    cl.close()
    srv.close()
    return got[0] / dt / 1e6


class _EchoPredictor:
    """Zero-compute predictor: the serving stack around it IS the
    measurement."""
    fetch_names = ["y"]

    def run(self, feed, fetch_names=None, return_numpy=True):
        return [np.ascontiguousarray(feed["x"][:1])]  # tiny response

    def clone(self):
        return self


def _turnaround_floor_mbps(n_requests=32, inflight=8):
    """The PROTOCOL's own ceiling on this host: a minimal inline
    request/response loop — identical framing (length-prefixed header +
    payload, vectored client send, recv_into server, tiny response),
    identical pipeline depth, but ZERO serving stack (no threads, no
    queues, no predictor). Whatever fraction of the raw firehose THIS
    loses is the cost of the request/response pattern itself (reverse
    traffic, per-request syscalls, one CPU running both ends), not of
    serving.py."""
    import json as _json
    import struct as _struct

    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    hdr = _json.dumps({"feeds": [{"name": "x", "dtype": "float32",
                                  "shape": [_PAYLOAD // 4]}]}).encode()

    def _srv_side():
        c, _ = srv.accept()
        c.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        buf = bytearray(_PAYLOAD)
        tiny = _struct.pack("<I", 2) + b"{}"
        try:
            for _ in range(n_requests):
                need = bytearray(4)
                mv = memoryview(need)
                while len(mv):
                    mv = mv[c.recv_into(mv, len(mv)):]
                hl, = _struct.unpack("<I", need)
                h = b""
                while len(h) < hl:
                    h += c.recv(hl - len(h))
                mv = memoryview(buf)
                while len(mv):
                    mv = mv[c.recv_into(mv, len(mv)):]
                c.sendall(tiny)
        finally:
            c.close()

    t = threading.Thread(target=_srv_side)
    t.start()
    from paddle_tpu.serving import _sendall_vec
    cl = socket.create_connection(srv.getsockname())
    cl.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    payload = np.zeros(_PAYLOAD // 4, np.float32)
    frame = [_struct.pack("<I", len(hdr)), hdr, payload]

    def _recv_resp():
        need = b""
        while len(need) < 4:
            need += cl.recv(4 - len(need))
        hl, = _struct.unpack("<I", need)
        h = b""
        while len(h) < hl:
            h += cl.recv(hl - len(h))

    t0 = time.time()
    sent = recvd = 0
    while recvd < n_requests:
        while sent < n_requests and sent - recvd < inflight:
            _sendall_vec(cl, frame)
            sent += 1
        _recv_resp()
        recvd += 1
    dt = time.time() - t0
    t.join()
    cl.close()
    srv.close()
    return n_requests * _PAYLOAD / dt / 1e6


def _served_wire_mbps(n_requests=48, inflight=8):
    """Sustained REQUEST wire rate through PredictorServer with a
    pipelined client: requests carry _PAYLOAD bytes, responses are tiny,
    so the measured direction is client->server — the same direction the
    raw probe measures."""
    from paddle_tpu.serving import PredictorClient, PredictorServer

    x = np.zeros((_PAYLOAD // 4,), np.float32)
    with PredictorServer(_EchoPredictor()) as srv:
        host, port = srv.address
        with PredictorClient(host, port) as c:
            c.infer({"x": x})                    # warm
            t0 = time.time()
            sent = recvd = 0
            while recvd < n_requests:
                while sent < n_requests and sent - recvd < inflight:
                    c.send({"x": x})
                    sent += 1
                c.recv()
                recvd += 1
            dt = time.time() - t0
    return n_requests * x.nbytes / dt / 1e6


def bench_transport(n_runs=3):
    """Three interleaved measurements per run on the SAME loopback:
    raw one-way firehose (link capacity), the inline zero-stack
    request/response floor, and the served wire rate. Utilization is
    served/raw; served/floor prices the serving stack against the
    protocol's own ceiling."""
    served, raws, floors = [], [], []
    for _ in range(n_runs):
        raw_a = _raw_link_mbps("127.0.0.1", None)
        floor = _turnaround_floor_mbps()
        wire = _served_wire_mbps()
        raw_b = _raw_link_mbps("127.0.0.1", None)
        raws.append(max(raw_a, raw_b))   # best same-run sample = capacity
        floors.append(floor)
        served.append(wire)
    utils = [s / r for s, r in zip(served, raws)]
    futils = [f / r for f, r in zip(floors, raws)]
    over_floor = [s / f for s, f in zip(served, floors)]
    # per-request CPU cost of the request/response pattern, from the floor
    floor_ms = _PAYLOAD / (float(np.mean(floors)) * 1e6) * 1e3
    raw_ms = _PAYLOAD / (float(np.mean(raws)) * 1e6) * 1e3
    served_ms = _PAYLOAD / (float(np.mean(served)) * 1e6) * 1e3
    # on a real serving link (the dev tunnel sustains ~24 MB/s, bench.py),
    # the measured per-request CPU cost is amortized over the wire time of
    # the same payload — the predicted utilization there
    tunnel_wire_ms = _PAYLOAD / 24e6 * 1e3
    pred_tunnel_util = tunnel_wire_ms / (tunnel_wire_ms
                                         + (served_ms - raw_ms))
    out = {"exp": "transport_link_utilization",
           "payload_bytes_per_request": _PAYLOAD,
           "pipeline_depth": 8,
           "raw_link_MBps": [round(x, 1) for x in raws],
           "turnaround_floor_MBps": [round(x, 1) for x in floors],
           "served_wire_MBps": [round(x, 1) for x in served],
           "served_link_utilization": round(float(np.mean(utils)), 3),
           "served_link_utilization_runs": [round(u, 3) for u in utils],
           "served_link_utilization_spread": [round(min(utils), 3),
                                              round(max(utils), 3)],
           "error_bar": round((max(utils) - min(utils)) / 2, 3),
           "turnaround_floor_utilization": round(float(np.mean(futils)),
                                                 3),
           "served_over_floor": round(float(np.mean(over_floor)), 3),
           "residual_attribution": {
               "per_request_ms": {"raw": round(raw_ms, 2),
                                  "floor": round(floor_ms, 2),
                                  "served": round(served_ms, 2)},
               "protocol_turnaround_ms": round(floor_ms - raw_ms, 2),
               "stack_overhead_ms": round(served_ms - floor_ms, 2),
               "predicted_tunnel_link_utilization":
                   round(pred_tunnel_util, 3),
               "note": "On this 2-core loopback the 'link' runs at memcpy "
                       "speed, so every per-request CPU cost is charged "
                       "against it: the zero-stack floor experiment shows "
                       "the request/response pattern ALONE forfeits "
                       "~half the firehose; the serving stack's own "
                       "addition is the smaller stack_overhead_ms "
                       "(reader/worker/writer handoffs that buy "
                       "compute/I-O overlap). On the actual serving link "
                       "(dev tunnel, ~24 MB/s measured in bench.py) the "
                       "same absolute per-request cost amortizes over "
                       "~175 ms of wire time per payload -> predicted "
                       "utilization above, vs the 0.54-0.71 the r05 "
                       "transport measured on that link.",
           }}
    print(json.dumps(out), flush=True)
    return out


def main():
    import jax

    sched = bench_scheduling()
    tx = bench_transport()
    print(json.dumps({
        "bench": "serve_ab", "round": 7,
        "device_kind": getattr(jax.devices()[0], "device_kind",
                               str(jax.devices()[0])),
        "claims": {
            "continuous_ge_1p5x_static_at_equal_slo": bool(
                sched["speedup_continuous_over_static"] >= 1.5
                and sched["equal_slo"]),
            "served_link_utilization_ge_0.85": bool(
                tx["served_link_utilization"] >= 0.85),
            # the acceptance's alternative branch: the sub-0.85 residual
            # is decomposed with numbers in residual_attribution (protocol
            # turnaround dominates; predicted utilization on the real
            # tunnel link is committed there)
            "residual_attributed_to_protocol_turnaround": bool(
                tx["served_link_utilization"] < 0.85
                and "residual_attribution" in tx),
        },
        "notes": "CPU-mesh measured (2-core box). The scheduling A/B "
                 "isolates admission policy: both sides run the identical "
                 "compiled slot-cache tick (fused decode chain, structure-"
                 "asserted in tests/test_serving_engine.py) — on TPU the "
                 "tick gets faster but the slot-occupancy ratio, which is "
                 "what the speedup measures, is hardware-independent. The "
                 "transport census removes the device entirely: utilization "
                 "is served wire rate over a same-run raw-socket probe on "
                 "the same loopback, so it prices framing + turnaround "
                 "only.",
    }), flush=True)


if __name__ == "__main__":
    main()
