#!/usr/bin/env python
"""BENCH_PP_r09 generator: pipeline-parallel executor evidence.

Commits, per the r09 acceptance bar:
- fixed-seed loss parity (3 steps) of gpipe AND 1f1b vs the single-device
  baseline on two models (deep MLP, conv net);
- bubble-fraction tables across M in {4,8,16}: the schedule-table census
  (exact) pinned against the analytic (K-1)/(M+K-1), plus measured
  step times and the slot-model fit;
- activation-liveness tables: 1F1B's peak stashed-microbatch count
  strictly below GPipe's at M >= 2*stages (asserted from the census);
- dp=2 x pp=2 composition parity, including ReduceStrategy.ReduceScatter
  (the r08 explicit gradient pipeline under pipeline mode);
- boundary wire bytes per step (ring accounting, shared
  probe_common/collective-permute model).

Usage:  python tools/bench_pp.py --out BENCH_PP_r09.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))


def _models():
    import paddle_tpu as pt
    from paddle_tpu import layers

    def mlp():
        x = layers.data("x", shape=[64])
        label = layers.data("label", shape=[1], dtype="int64")
        h = x
        for _ in range(6):
            h = layers.fc(h, size=128, act="relu")
        loss = layers.mean(layers.softmax_with_cross_entropy(
            layers.fc(h, size=10), label))
        pt.optimizer.MomentumOptimizer(0.1, momentum=0.9).minimize(loss)
        return loss

    def conv():
        img = layers.data("img", shape=[8, 8, 3])
        label = layers.data("label", shape=[1], dtype="int64")
        h = layers.conv2d(img, 8, 3, padding=1, act="relu",
                          data_format="NHWC")
        h = layers.pool2d(h, 2, "max", 2, data_format="NHWC")
        h = layers.conv2d(h, 16, 3, padding=1, act="relu",
                          data_format="NHWC")
        h = layers.pool2d(h, 2, "max", 2, data_format="NHWC")
        h = layers.fc(h, size=32, act="relu")
        loss = layers.mean(layers.softmax_with_cross_entropy(
            layers.fc(h, size=10), label))
        pt.optimizer.SGDOptimizer(0.1).minimize(loss)
        return loss

    import numpy as np

    def mlp_feed(i, bs):
        return {"x": np.random.RandomState(100 + i)
                .rand(bs, 64).astype("f4"),
                "label": np.random.RandomState(200 + i)
                .randint(0, 10, (bs, 1)).astype("i8")}

    def conv_feed(i, bs):
        return {"img": np.random.RandomState(300 + i)
                .rand(bs, 8, 8, 3).astype("f4"),
                "label": np.random.RandomState(400 + i)
                .randint(0, 10, (bs, 1)).astype("i8")}

    return {"mlp": (mlp, mlp_feed), "conv": (conv, conv_feed)}


def _fresh(build):
    import paddle_tpu as pt
    pt.reset_default_programs()
    pt.reset_global_scope()
    with pt.core.unique_name.guard():
        loss = build()
    return loss


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out", default=None)
    p.add_argument("--steps", type=int, default=3)
    p.add_argument("--iters", type=int, default=5)
    args = p.parse_args()

    import numpy as np
    import jax
    import paddle_tpu as pt
    from paddle_tpu.parallel import ParallelExecutor
    from paddle_tpu.parallel.mesh import DeviceMesh
    from paddle_tpu.parallel.pipeline import (pp_boundary_wire_bytes,
                                              schedule_census)
    from paddle_tpu.parallel.strategy import BuildStrategy, ReduceStrategy

    models = _models()
    result = {"bench": "pipeline_parallel_r09",
              "device": jax.devices()[0].platform,
              "device_count": len(jax.devices()),
              "steps": args.steps, "parity": {}, "bubble": {},
              "stash": [], "dpxpp": {}}

    def run_pipeline(build, feeds, loss_getter, axes, stages, m, sched,
                     rs=ReduceStrategy.AllReduce):
        loss = _fresh(build)
        bst = BuildStrategy(pipeline_stages=stages, num_microbatches=m,
                            pipeline_schedule=sched)
        bst.reduce_strategy = rs
        n = 1
        for s in axes.values():
            n *= s
        mesh = DeviceMesh(jax.devices()[:n], axes)
        exe = ParallelExecutor(loss_name=loss.name, mesh=mesh,
                               build_strategy=bst)
        pt.Executor().run(pt.default_startup_program())
        losses = [float(exe.run(feed=f, fetch_list=[loss])[0])
                  for f in feeds]
        return losses, exe, loss

    # --- parity: single device vs gpipe vs 1f1b on two models -----------
    for name, (build, mk_feed) in models.items():
        feeds = [mk_feed(i, 16) for i in range(args.steps)]
        loss = _fresh(build)
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        base = [float(exe.run(feed=f, fetch_list=[loss])[0])
                for f in feeds]
        row = {"single_device": base}
        for sched in ("gpipe", "1f1b"):
            got, _, _ = run_pipeline(build, feeds, None, {"pp": 2}, 2, 4,
                                     sched)
            row[sched] = got
            row[f"{sched}_max_abs_diff"] = float(
                max(abs(a - b) for a, b in zip(base, got)))
            assert row[f"{sched}_max_abs_diff"] <= 1e-5, (name, sched, row)
        result["parity"][name] = row

    # --- bubble tables: M in {4,8,16}, K in {2,4} ------------------------
    build, mk_feed = models["mlp"]
    for k in (2, 4):
        for sched in ("gpipe", "1f1b"):
            rows = []
            for m in (4, 8, 16):
                feeds = [mk_feed(0, m * 4)]
                _, exe, loss = run_pipeline(build, feeds, None, {"pp": k},
                                            k, m, sched)
                t0 = time.time()
                out = None
                for _ in range(args.iters):
                    out = exe.run(feed=feeds[0], fetch_list=[loss],
                                  return_numpy=False)
                float(np.asarray(out[0]).ravel()[0])
                step_ms = (time.time() - t0) / args.iters * 1e3
                census = schedule_census(sched, m, k)
                prog = exe._prepare_program(pt.default_main_program(),
                                            pt.global_scope())
                wire = pp_boundary_wire_bytes(prog, 4)
                assert census["bubble_fraction"] == census[
                    "analytic_bubble_fraction"], census
                rows.append({
                    "M": m, "ticks": census["ticks"],
                    "step_ms": round(step_ms, 2),
                    "bubble_fraction": census["bubble_fraction"],
                    "analytic": census["analytic_bubble_fraction"],
                    "pp_boundary_bytes_per_step":
                        wire["pp_boundary_bytes"],
                })
            result["bubble"][f"K{k}_{sched}"] = rows

    # --- activation-liveness (stash) census ------------------------------
    for k in (2, 4):
        for m in sorted({2 * k, 4 * k, 16}):
            g = schedule_census("gpipe", m, k)
            f = schedule_census("1f1b", m, k)
            assert f["peak_stash"] < g["peak_stash"], (m, k)
            result["stash"].append({
                "K": k, "M": m,
                "gpipe_peak_stash": g["peak_stash"],
                "gpipe_per_stage": g["peak_stash_per_stage"],
                "1f1b_peak_stash": f["peak_stash"],
                "1f1b_per_stage": f["peak_stash_per_stage"],
                "1f1b_strictly_below_gpipe": True,
            })

    # --- dp x pp composition ---------------------------------------------
    build, mk_feed = models["mlp"]
    feeds = [mk_feed(i, 16) for i in range(args.steps)]
    loss = _fresh(build)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    base = [float(exe.run(feed=f, fetch_list=[loss])[0]) for f in feeds]
    result["dpxpp"]["single_device"] = base
    for label, rs in (("allreduce", ReduceStrategy.AllReduce),
                      ("reduce_scatter", ReduceStrategy.ReduceScatter)):
        got, _, _ = run_pipeline(build, feeds, None, {"dp": 2, "pp": 2},
                                 2, 4, "1f1b", rs=rs)
        result["dpxpp"][label] = got
        result["dpxpp"][f"{label}_max_abs_diff"] = float(
            max(abs(a - b) for a, b in zip(base, got)))
        assert result["dpxpp"][f"{label}_max_abs_diff"] <= 1e-5

    result["notes"] = (
        "All ms numbers are CPU-mesh (8 virtual devices, 2-core box); "
        "parity, bubble-census and stash claims are exact properties of "
        "the compiled schedule/HLO and transfer to TPU unchanged. "
        "bubble_fraction is read from the executed tick tables and equals "
        "the analytic (K-1)/(M+K-1) identically for both schedules; "
        "1F1B's win is the bounded activation stash, asserted per row.")
    text = json.dumps(result, indent=1)
    if args.out:
        with open(args.out, "w") as fo:
            fo.write(text + "\n")
    print(text)


if __name__ == "__main__":
    main()
