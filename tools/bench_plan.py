#!/usr/bin/env python
"""BENCH_PLAN: the r19 auto-parallel planner validation artifact.

Planner-chosen strategy vs every hand-picked strategy on mnist +
transformer_lm over 2- and 4-device slices of the virtual CPU mesh
(ISSUE 15's acceptance cells). Per cell:

  - the planner searches the full joint space (framework/auto_parallel)
    with TVM-style measured refinement: the best-predicted point of each
    of the top strategy FAMILIES is measured for real and the
    measured-best wins (`measure_fn`/`measure_k`) — the honest protocol
    on a mesh whose constants differ from the v5e model's;
  - the chosen strategy and every hand-picked one then run INTERLEAVED
    (round-robin steps, per-config median, the r18 IQR noise-floor
    discipline) so all configs share every noise source;
  - the executed CHOICE commits the wire-byte balance: the cost ledger's
    predicted per-step collective bytes must equal the HLO census
    EXACTLY (observability/ledger.py check_wire_bytes_exact);
  - checks: `planner_matches_or_beats` (chosen median <= best hand
    median within the band = max(2%, measured IQR)), and
    `predict_measure_consistent` — the planner never ranks a strategy
    predicted-better yet measured-worse beyond the band among the
    measured points (tests/test_auto_parallel.py re-asserts both over
    the committed artifact).

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python tools/bench_plan.py --out BENCH_PLAN_r19.json

Byte/feasibility/rank claims are exact properties of the compiled
programs and transfer to TPU unchanged; ms medians are CPU-mesh.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _build_model(model, batch, rng):
    import paddle_tpu as pt
    from paddle_tpu import layers
    if model == "mnist":
        x = layers.data("x", shape=[64])
        label = layers.data("label", shape=[1], dtype="int64")
        h = layers.fc(x, size=128, act="relu")
        h2 = layers.fc(h, size=64, act="relu")
        loss = layers.mean(layers.softmax_with_cross_entropy(
            layers.fc(h2, size=10), label))
        pt.optimizer.MomentumOptimizer(0.1, momentum=0.9).minimize(loss)
        feed = {"x": rng.rand(batch, 64).astype("float32"),
                "label": rng.randint(0, 10, (batch, 1)).astype("int64")}
        return loss, feed
    from paddle_tpu.models import transformer
    T, vocab = 32, 128
    loss, _ = transformer.transformer_lm(
        vocab=vocab, max_len=T, d_model=64, d_inner=128, num_heads=4,
        num_layers=2, dropout=0.0, mean_loss=True)
    from paddle_tpu.parallel import annotate_tp
    assert annotate_tp(), "annotate_tp matched nothing"
    pt.optimizer.AdamOptimizer(1e-3).minimize(loss)
    feed = {"tokens": rng.randint(0, vocab, (batch, T)).astype("int64"),
            "tokens@SEQLEN": np.full((batch,), T, "int32"),
            "targets": rng.randint(0, vocab, (batch, T)).astype("int64")}
    return loss, feed


#: hand-picked strategies per (model, device count) — the r08/r09/r11
#: bench configurations the planner must match or beat
def _hand_points(model, n):
    from paddle_tpu.framework.auto_parallel import StrategyPoint
    pts = {
        f"dp{n}-allreduce": StrategyPoint(dp=n),
        f"dp{n}-reduce_scatter": StrategyPoint(dp=n,
                                               reduce="reduce_scatter"),
        f"dp{n // 2}xpp2-1f1b-m4": StrategyPoint(dp=n // 2, pp=2,
                                                 microbatches=4),
    }
    if model == "transformer_lm":
        pts[f"dp{n // 2}xtp2-reduce_scatter"] = StrategyPoint(
            dp=n // 2, tp=2, reduce="reduce_scatter")
    return pts


class _Cell:
    """One (model, devices) cell: builds a fresh program/scope/executor
    per strategy point (interleaved timing must not thrash shared state
    placement between differently-sharded configs)."""

    def __init__(self, model, n_devices, batch):
        self.model = model
        self.n = n_devices
        self.batch = batch
        self.runners = {}

    def runner(self, point):
        import jax
        import paddle_tpu as pt
        from paddle_tpu.parallel import ParallelExecutor
        from paddle_tpu.parallel.mesh import DeviceMesh
        point = point.canonical()
        r = self.runners.get(point)
        if r is not None:
            return r
        pt.reset_default_programs()
        pt.reset_global_scope()
        rng = np.random.RandomState(7)
        with pt.core.unique_name.guard():
            loss, feed = _build_model(self.model, self.batch, rng)
        prog = pt.default_main_program()
        exe = ParallelExecutor(
            loss_name=loss.name,
            build_strategy=point.to_build_strategy(),
            mesh=DeviceMesh(jax.devices()[:self.n], point.mesh_axes()),
            main_program=prog, scope=pt.global_scope())
        pt.Executor().run(pt.default_startup_program())

        def step():
            import jax as _jax
            _jax.block_until_ready(exe.run(feed=feed, fetch_list=[loss],
                                           return_numpy=False))
        step()                                    # compile + warm
        r = {"point": point, "exe": exe, "prog": prog, "loss": loss,
             "feed": feed, "step": step}
        self.runners[point] = r
        return r

    def quick_median(self, point, steps=9):
        r = self.runner(point)
        r["step"]()
        ts = []
        for _ in range(steps):
            t0 = time.perf_counter()
            r["step"]()
            ts.append(time.perf_counter() - t0)
        return sorted(ts)[len(ts) // 2]


def run_cell(model, n, batch, rounds, measure_k, anneal_iters, seed):
    import jax
    import paddle_tpu as pt
    from paddle_tpu.framework import auto_parallel, costs as _costs
    from paddle_tpu.observability.ledger import CostLedger

    cell = _Cell(model, n, batch)
    rng = np.random.RandomState(7)
    pt.reset_default_programs()
    pt.reset_global_scope()
    with pt.core.unique_name.guard():
        _build_model(model, batch, rng)
    plan_prog = pt.default_main_program()

    t0 = time.time()
    result = auto_parallel.plan(
        plan_prog, n, nominal_batch=batch, anneal_iters=anneal_iters,
        seed=seed, measure_k=measure_k,
        measure_fn=lambda row: cell.quick_median(row["point"]))
    search_s = time.time() - t0

    hand = _hand_points(model, n)
    executed = {"planner_choice": result.point}
    for name, pt_ in hand.items():
        executed[name] = pt_.canonical()

    # interleaved-median timing: every executed config steps once per
    # round, medians share every noise source (r18 discipline)
    samples = {name: [] for name in executed}
    for name in executed:
        cell.runner(executed[name])["step"]()     # all warm before timing
    for _ in range(rounds):
        for name, point in executed.items():
            r = cell.runner(point)
            t1 = time.perf_counter()
            r["step"]()
            samples[name].append(time.perf_counter() - t1)

    def _med_iqr(ts):
        med = sorted(ts)[len(ts) // 2]
        q1, q3 = np.percentile(ts, [25, 75])
        return med, float((q3 - q1) / max(med, 1e-9))

    rows = {}
    for name, point in executed.items():
        med, iqr = _med_iqr(samples[name])
        rank = result.rank_of(point)
        pred = next((r["predicted_s"] for r in result.ranking
                     if r["point"] == point), None)
        rows[name] = {"point": point.describe(),
                      "plan_predicted_ms":
                          (round(pred * 1e3, 6) if pred is not None
                           else None),
                      "plan_rank": rank,
                      "measured_ms": round(med * 1e3, 3),
                      "iqr_rel": round(iqr, 4)}

    choice_row = rows["planner_choice"]
    hand_rows = {k: v for k, v in rows.items() if k != "planner_choice"}
    best_hand = min(hand_rows, key=lambda k: hand_rows[k]["measured_ms"])
    band = max(0.02, hand_rows[best_hand]["iqr_rel"],
               choice_row["iqr_rel"])
    checks = []

    ok_beats = (choice_row["measured_ms"]
                <= hand_rows[best_hand]["measured_ms"] * (1 + band))
    checks.append({"name": "planner_matches_or_beats",
                   "chosen_ms": choice_row["measured_ms"],
                   "best_hand": best_hand,
                   "best_hand_ms": hand_rows[best_hand]["measured_ms"],
                   "band": round(band, 4), "ok": bool(ok_beats)})

    # property (b): among the measured configs, predicted-better must
    # never be measured-worse beyond the band
    violations = []
    named = list(rows.items())
    for i, (na, a) in enumerate(named):
        for nb, b in named[i + 1:]:
            pa, pb = a["plan_predicted_ms"], b["plan_predicted_ms"]
            if pa is None or pb is None:
                continue
            lo, hi = (a, b) if pa <= pb else (b, a)
            if lo["measured_ms"] > hi["measured_ms"] * (1 + band):
                violations.append({"predicted_better": lo["point"],
                                   "measured_better": hi["point"],
                                   "gap": round(lo["measured_ms"]
                                                / hi["measured_ms"] - 1,
                                                4)})
    checks.append({"name": "predict_measure_consistent",
                   "violations": violations, "band": round(band, 4),
                   "ok": not violations})

    # exact wire-byte balance on the EXECUTED planner choice
    r = cell.runner(result.point)
    exe = r["exe"]
    led_row = CostLedger("bench_plan").row(f"{model}_n{n}_choice")
    led_row.set_prediction(exe.cost_report(nominal_batch=batch))
    import jax.numpy as jnp
    cs = list(exe._cache.values())[-1]
    scope = exe.scope
    hlo = cs.fn.lower(
        tuple(jnp.asarray(r["feed"][x]) for x in cs.feed_names),
        tuple(scope.get(x) for x in cs.ro_names),
        tuple(scope.get(x) for x in cs.rw_names),
        np.uint32(0)).compile().as_text()
    census = _costs.collective_census(hlo)
    dp = exe.mesh.axis_size("dp")
    led_row.set_census(census, dp, min_bytes=8)
    wire = led_row.check_wire_bytes_exact()
    checks.append({"name": "wire_bytes_exact_on_choice", **{
        k: wire[k] for k in ("predicted", "measured", "ok")}})

    return {
        "model": model, "devices": n, "batch_size": batch,
        "rounds": rounds,
        "plan": result.summary(),
        "plan_search_s": round(search_s, 3),
        "configs": rows,
        "chosen": choice_row["point"],
        "best_hand": best_hand,
        "checks": checks,
        "ok": all(c["ok"] for c in checks),
    }


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out", default="BENCH_PLAN_r19.json")
    p.add_argument("--rounds", type=int, default=20)
    p.add_argument("--measure_k", type=int, default=6)
    p.add_argument("--anneal_iters", type=int, default=64)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--cells", default="",
                   help="comma list model:devices (default: the full "
                        "mnist/transformer_lm x 2/4 matrix)")
    args = p.parse_args()

    from paddle_tpu.core import flags as _flags
    _flags.set_flag("use_bf16_matmul", False)

    cells = [("mnist", 2), ("mnist", 4),
             ("transformer_lm", 2), ("transformer_lm", 4)]
    if args.cells:
        cells = [(m, int(d)) for m, d in
                 (c.split(":") for c in args.cells.split(","))]

    out = {"bench": "BENCH_PLAN", "round": "r19",
           "note": ("planner-chosen vs hand-picked strategies; "
                    "interleaved per-config medians on the virtual CPU "
                    "mesh; wire-byte balance exact on the executed "
                    "choice; ms numbers are CPU-mesh, byte/rank claims "
                    "transfer to TPU unchanged"),
           "cells": []}
    for model, n in cells:
        print(f"== {model} x {n} devices ==", file=sys.stderr)
        cell = run_cell(model, n, batch=32, rounds=args.rounds,
                        measure_k=args.measure_k,
                        anneal_iters=args.anneal_iters, seed=args.seed)
        out["cells"].append(cell)
        print(json.dumps({k: cell[k] for k in
                          ("model", "devices", "chosen", "best_hand",
                           "ok")}), file=sys.stderr)
    out["ok"] = all(c["ok"] for c in out["cells"])
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(f"wrote {args.out}: ok={out['ok']}", file=sys.stderr)
    sys.exit(0 if out["ok"] else 1)


if __name__ == "__main__":
    main()
