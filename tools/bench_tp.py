#!/usr/bin/env python
"""BENCH_TP_r11 generator: tensor-parallel subsystem evidence.

Commits, per the r11 acceptance bar:
- fixed-seed loss parity (3 steps) of the tp_shard_pass + full-manual
  shard_map path vs the single-device baseline for tp2, dp2 x tp2, and
  dp2 x pp2 x tp2 (1F1B) configurations of the transformer builder on the
  CPU mesh, ReduceScatter mode throughout (f32 matmuls: splitting a bf16
  contraction over tp changes its rounding);
- the analytic tp-collective wire model (framework/sharding.py ring
  accounting, shared probe_common.collective_wire_bytes discipline)
  asserted EXACTLY against the compiled step's HLO all-reduce census on
  the dp=1 x tp=2 mesh, plus per-kind tp op counts;
- measured step times per configuration (CPU-mesh context numbers, not a
  TPU speed claim — the tp win is wider-than-one-chip capacity).

Usage:  JAX_PLATFORMS=cpu python tools/bench_tp.py --out BENCH_TP_r11.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))

VOCAB, T, D, HEADS, LAYERS, BS = 64, 16, 64, 4, 2, 8


def _build():
    import paddle_tpu as pt
    from paddle_tpu.models import transformer
    loss, _ = transformer.transformer_lm(
        vocab=VOCAB, max_len=T, d_model=D, d_inner=2 * D,
        num_heads=HEADS, num_layers=LAYERS, mean_loss=True)
    pt.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    return loss


def _feeds(n=3):
    import numpy as np
    rng = np.random.RandomState(7)
    return [{"tokens": rng.randint(0, VOCAB, (BS, T)).astype("int64"),
             "tokens@SEQLEN": np.full((BS,), T, dtype="int32"),
             "targets": rng.randint(0, VOCAB, (BS, T)).astype("int64")}
            for _ in range(n)]


def _baseline(feeds):
    import paddle_tpu as pt
    pt.reset_default_programs()
    pt.reset_global_scope()
    with pt.core.unique_name.guard():
        loss = _build()
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    return [float(exe.run(feed=f, fetch_list=[loss])[0]) for f in feeds]


def _tp_run(feeds, axes, stages=0, micro=0, iters=10):
    import jax
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu.parallel import ParallelExecutor, annotate_tp
    from paddle_tpu.parallel.mesh import DeviceMesh
    from paddle_tpu.parallel.strategy import BuildStrategy, ReduceStrategy

    pt.reset_default_programs()
    pt.reset_global_scope()
    with pt.core.unique_name.guard():
        loss = _build()
    annotate_tp()
    pt.Executor().run(pt.default_startup_program())
    n = 1
    for s in axes.values():
        n *= s
    kw = {}
    if stages:
        kw = dict(pipeline_stages=stages, num_microbatches=micro)
    bst = BuildStrategy(**kw)
    bst.reduce_strategy = ReduceStrategy.ReduceScatter
    mesh = DeviceMesh(jax.devices()[:n], axes)
    pe = ParallelExecutor(loss_name=loss.name, mesh=mesh,
                          build_strategy=bst)
    losses = [float(pe.run(feed=f, fetch_list=[loss])[0]) for f in feeds]
    t0 = time.time()
    out = None
    for _ in range(iters):
        out = pe.run(feed=feeds[-1], fetch_list=[loss],
                     return_numpy=False)
    jax.block_until_ready(out)
    step_ms = (time.time() - t0) / iters * 1000
    return losses, pe, loss, round(step_ms, 2)


def _census_fields(pe, feed, tp):
    import jax.numpy as jnp
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu.framework.sharding import tp_analytic_wire_bytes
    from probe_common import collective_census

    scope = pt.global_scope()
    prog = pe._prepare_program(pt.default_main_program(), scope)
    w = tp_analytic_wire_bytes(prog, tp, nominal_batch=BS)
    cs = list(pe._cache.values())[-1]
    feed_vals = tuple(jnp.asarray(feed[n]) for n in cs.feed_names)
    ro = tuple(scope.get(n) for n in cs.ro_names)
    rw = tuple(scope.get(n) for n in cs.rw_names)
    hlo = cs.fn.lower(feed_vals, ro, rw, np.uint32(0)).compile().as_text()
    census = collective_census(hlo)
    ar_census_out_bytes = sum(b for b, _ in census.get("all-reduce", [])
                              if b >= 8)
    ar_analytic_out_bytes = int(
        w["tp_allreduce_wire_bytes"] / (2 * (tp - 1) / tp))
    return {
        "tp": tp,
        "tp_allreduce_bytes_on_wire": w["tp_allreduce_wire_bytes"],
        "tp_allgather_bytes_on_wire": w["tp_allgather_wire_bytes"],
        "tp_wire_bytes_per_step": w["tp_wire_bytes"],
        "tp_collective_counts": w["tp_op_counts"],
        "census_allreduce_out_bytes": ar_census_out_bytes,
        "analytic_allreduce_out_bytes": ar_analytic_out_bytes,
        "census_matches_analytic":
            ar_census_out_bytes == ar_analytic_out_bytes,
        "census_collectives": {k: len(v) for k, v in census.items()},
    }


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--out", default="BENCH_TP_r11.json")
    p.add_argument("--iters", type=int, default=10)
    args = p.parse_args()

    sys.path.insert(0, REPO)
    from __graft_entry__ import _ensure_virtual_cpu_devices
    _ensure_virtual_cpu_devices(8)
    import jax
    from paddle_tpu.core import flags
    flags.set_flag("use_bf16_matmul", False)

    feeds = _feeds()
    base = _baseline(feeds)
    doc = {
        "bench": "tensor_parallel_r11",
        "device": jax.devices()[0].platform,
        "device_count": len(jax.devices()),
        "model": {"builder": "transformer_lm", "vocab": VOCAB,
                  "max_len": T, "d_model": D, "num_heads": HEADS,
                  "num_layers": LAYERS, "batch_size": BS,
                  "reduce_mode": "reduce_scatter",
                  "matmul_dtype": "f32"},
        "steps": len(feeds),
        "parity": {"single_device": base},
    }

    configs = [("tp2", {"dp": 1, "tp": 2}, 0, 0),
               ("dp2_tp2", {"dp": 2, "tp": 2}, 0, 0),
               ("dp2_pp2_tp2_1f1b", {"dp": 2, "pp": 2, "tp": 2}, 2, 4)]
    census_pe = None
    for name, axes, stages, micro in configs:
        losses, pe, _, step_ms = _tp_run(feeds, axes, stages, micro,
                                         iters=args.iters)
        diff = max(abs(a - b) for a, b in zip(losses, base))
        assert diff <= 1e-5, f"{name}: parity {diff} > 1e-5"
        doc["parity"][name] = losses
        doc["parity"][f"{name}_max_abs_diff"] = diff
        doc.setdefault("step_ms", {})[name] = step_ms
        if name == "tp2":
            census_pe = pe

    doc["wire"] = _census_fields(census_pe, feeds[-1], 2)
    assert doc["wire"]["census_matches_analytic"], doc["wire"]

    with open(os.path.join(REPO, args.out), "w") as f:
        json.dump(doc, f, indent=1)
    print(json.dumps(doc, indent=1))


if __name__ == "__main__":
    main()
