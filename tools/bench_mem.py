"""BENCH_MEM: the r17 measured-vs-predicted MEMORY ledger artifact.

Closes the loop the r12 ledger left open: `costs.predict()["memory"]`
was a pure static estimate with no measured side. Each cell below runs
one program x parallel config on the virtual 8-device CPU mesh and
commits the ACCOUNTING IDENTITY (observability/ledger.py
check_memory_identity):

  predicted  costs.predict over the program AS RUN — per-device state/
             feed/transient byte categories from declared shapes +
             placement markers (costs.memory_categories)
  measured   observability.memory.device_memory_census — per-device
             state bytes from the ACTUAL device arrays, the XLA
             executable's argument/output/temp/alias figures
             (memory_analysis; HLO liveness-walk fallback documented in
             `temp_source`), and a live-array sweep
  checks     per-category bytes EXACT (params / optimizer_state /
             ef_residual / other_state / feeds), the category walk
             re-derives XLA's own argument figure within 64 bytes, and
             unattributed measured bytes <= 10% of the measured peak

plus the MFU sensor (`costs.mfu` over the blocked-measured step time)
per cell, and a LIVE-SURFACE smoke: one /metrics scrape and one Chrome
trace export must both carry the `ptpu_memory_*` / `ptpu_mfu` series
and the `memory/*` counter events.

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python tools/bench_mem.py --out BENCH_MEM_r17.json

Byte/category checks are exact properties of the compiled executable
and transfer to TPU unchanged; ms/MFU numbers are CPU-mesh.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _build_mnist_mlp(rng, batch):
    import paddle_tpu as pt
    from paddle_tpu import layers
    x = layers.data("x", shape=[64])
    label = layers.data("label", shape=[1], dtype="int64")
    h = layers.fc(x, size=128, act="relu")
    h2 = layers.fc(h, size=64, act="relu")
    loss = layers.mean(layers.softmax_with_cross_entropy(
        layers.fc(h2, size=10), label))
    pt.optimizer.MomentumOptimizer(0.1, momentum=0.9).minimize(loss)
    feed = {"x": rng.rand(batch, 64).astype("float32"),
            "label": rng.randint(0, 10, (batch, 1)).astype("int64")}
    return loss, feed


def _build_transformer_lm(rng, batch, tp=0, big=False):
    import paddle_tpu as pt
    from paddle_tpu.models import transformer
    # `big`: the r18 memory-plan cells — activation-stash-dominated
    # shapes (T=64, d=64) where the remat-vs-stash curve has room; the
    # r17 identity cells keep the original tiny config
    T, vocab, d_model, d_inner = (64, 128, 64, 128) if big \
        else (8, 64, 32, 64)
    loss, _ = transformer.transformer_lm(
        vocab=vocab, max_len=T, d_model=d_model, d_inner=d_inner,
        num_heads=4, num_layers=2, dropout=0.0, mean_loss=True)
    if tp > 1:
        from paddle_tpu.parallel import annotate_tp
        assert annotate_tp(), "annotate_tp matched nothing"
    pt.optimizer.AdamOptimizer(1e-3).minimize(loss)
    feed = {"tokens": rng.randint(0, vocab, (batch, T)).astype("int64"),
            "tokens@SEQLEN": np.full((batch,), T, "int32"),
            "targets": rng.randint(0, vocab, (batch, T)).astype("int64")}
    return loss, feed


#: cell -> (model, mode); modes cover {plain, dp2, dp2_ef, pp2,
#: dp2xpp2, tp2} — ISSUE 13 asks >= 4 program x parallel-config cells
CELLS = [
    ("mnist", "plain"),
    ("mnist", "dp2"),
    ("mnist", "dp2_ef"),
    ("mnist", "pp2"),
    ("mnist", "dp2xpp2"),
    ("transformer_lm", "plain"),
    ("transformer_lm", "dp2"),
    ("transformer_lm", "tp2"),
]

#: the --plan matrix (BENCH_MEMPLAN_r18.json): every cell runs its
#: planned twin (memory_plan_pass / BuildStrategy.memory_plan) next to
#: the unplanned baseline and commits the MEASURED census delta +
#: step-time ratio + the r17 identity on the planned cell.
#: transformer_lm_big is the activation-dominated shape (T=64, d=64,
#: batch below) where the remat-vs-stash search has real room; the
#: r17-config cells pin that planning tiny programs stays safe/neutral.
PLAN_CELLS = [
    ("mnist", "plain"),
    ("mnist", "dp2"),
    ("transformer_lm", "plain"),
    ("transformer_lm", "dp2"),
    ("transformer_lm", "tp2"),
    ("transformer_lm_big", "plain"),
    ("transformer_lm_big", "dp2"),
]
PLAN_BATCH = {"transformer_lm_big": 64}


def run_cell(led, model, mode, batch, iters, plan=False, time_frac=0.02):
    import jax
    import paddle_tpu as pt
    from paddle_tpu.core import flags as _flags
    from paddle_tpu.framework import costs as _costs
    from paddle_tpu.parallel import ParallelExecutor
    from paddle_tpu.parallel.mesh import DeviceMesh
    from paddle_tpu.parallel.strategy import BuildStrategy, ReduceStrategy

    _flags.set_flag("use_bf16_matmul", False)
    rng = np.random.RandomState(7)
    pt.reset_default_programs()
    pt.reset_global_scope()
    tp = 2 if mode == "tp2" else 0
    with pt.core.unique_name.guard():
        if model == "mnist":
            loss, feed = _build_mnist_mlp(rng, batch)
        else:
            loss, feed = _build_transformer_lm(
                rng, batch, tp=tp, big=model == "transformer_lm_big")

    bst = BuildStrategy()
    if mode != "pp2":   # a pp-only mesh has no dp axis for explicit comm
        bst.reduce_strategy = ReduceStrategy.ReduceScatter
    mesh = None
    dp = 1
    if mode in ("dp2", "dp2_ef"):
        mesh = DeviceMesh(jax.devices()[:2], {"dp": 2})
        dp = 2
        if mode == "dp2_ef":
            bst.quant_comm = "int8"
            bst.comm_error_feedback = True
    elif mode == "pp2":
        bst.pipeline_stages = 2
        bst.num_microbatches = 4
        bst.pipeline_schedule = "1f1b"
        mesh = DeviceMesh(jax.devices()[:2], {"pp": 2})
    elif mode == "dp2xpp2":
        bst.pipeline_stages = 2
        bst.num_microbatches = 4
        bst.pipeline_schedule = "1f1b"
        mesh = DeviceMesh(jax.devices()[:4], {"dp": 2, "pp": 2})
        dp = 2
    elif mode == "tp2":
        mesh = DeviceMesh(jax.devices()[:2], {"dp": 1, "tp": 2})

    if mode == "plain":
        exe = pt.Executor()
        pt.Executor().run(pt.default_startup_program())
        run = lambda: exe.run(feed=feed, fetch_list=[loss],  # noqa: E731
                              return_numpy=False)
    else:
        exe = ParallelExecutor(loss_name=loss.name, build_strategy=bst,
                               mesh=mesh)
        pt.Executor().run(pt.default_startup_program())
        run = lambda: exe.run(feed=feed, fetch_list=[loss],  # noqa: E731
                              return_numpy=False)

    out = run()                                   # compile + warm
    jax.block_until_ready(out)
    t0 = time.time()
    for _ in range(iters):
        out = run()
    jax.block_until_ready(out)
    step_s = (time.time() - t0) / iters

    run2 = None
    if plan:
        # the planned twin: same model/mode, the memory planner applied
        # to the program AS RUN. The measured-step budget is recorded on
        # the plan (and would GATE candidates under the mandated-recompute
        # mode, memory_plan_prevent_cse=True); the default CSE-able plan
        # is time-safe by construction — the band check below is what
        # holds its measured step to the bar
        budget_s = time_frac * step_s
        if mode == "plain":
            from paddle_tpu.framework.passes import get_pass
            planned_prog = get_pass(
                "memory_plan_pass", nominal_batch=batch,
                time_budget_s=budget_s)(pt.default_main_program())
            exe2 = pt.Executor()
            run2 = lambda: exe2.run(  # noqa: E731
                program=planned_prog, feed=feed, fetch_list=[loss],
                return_numpy=False)
        else:
            import dataclasses
            bst2 = dataclasses.replace(
                bst, memory_plan=True, memory_plan_time_budget_s=budget_s)
            exe2 = ParallelExecutor(loss_name=loss.name,
                                    build_strategy=bst2, mesh=mesh)
            run2 = lambda: exe2.run(  # noqa: E731
                feed=feed, fetch_list=[loss], return_numpy=False)
        jax.block_until_ready(run2())             # compile + warm
        # interleaved timing: planned and unplanned share every noise
        # source (load, caches), the ratio is what the band checks.
        # Sub-millisecond cells need many samples before a 2% band means
        # anything — scale the pair count to ~1s of total timing
        iters = min(400, max(iters, int(1.0 / max(2 * step_s, 2.5e-3))))
        ts_u, ts_p = [], []
        for _ in range(iters):
            a = time.perf_counter()
            jax.block_until_ready(run())
            ts_u.append(time.perf_counter() - a)
            a = time.perf_counter()
            jax.block_until_ready(run2())
            ts_p.append(time.perf_counter() - a)
        step_s = sorted(ts_u)[len(ts_u) // 2]
        step2_s = sorted(ts_p)[len(ts_p) // 2]
        # the band's noise floor: a hard 2% gate on a millisecond CPU
        # step is flakier than the thing it measures — use the UNPLANNED
        # side's own relative IQR as the floor and record it
        q1, q3 = np.percentile(ts_u, [25, 75])
        noise_rel = float((q3 - q1) / max(step_s, 1e-9))
        time_band = max(0.02, noise_rel)

    if mode == "plain":
        predicted = _costs.predict(pt.default_main_program(), dp=1,
                                   nominal_batch=batch)
    else:
        predicted = exe.cost_report(nominal_batch=batch)
    census = exe.memory_census(feed=feed)

    ndev = max(1, int(getattr(exe, "device_count", 1)))
    flops = predicted["compute"]["flops"]
    cell_mfu = _costs.mfu(flops / ndev, step_s)

    row = led.row(f"{model}_{mode}", model=model, mode=mode,
                  batch_size=batch, devices=ndev, dp=dp)
    row.set_prediction(predicted)
    row.set_memory_census(census)
    row.set_measured(step_ms=round(step_s * 1e3, 3), iters=iters,
                     mfu=cell_mfu,
                     temp_source=census["xla"]["temp_source"])
    rec = row.check_memory_identity(residual_frac=0.10)
    row._check("mfu_positive", ">0", round(cell_mfu, 10), ">0",
               cell_mfu > 0)

    if plan:
        if mode == "plain":
            census2 = exe2.memory_census(feed=feed, program=planned_prog)
            predicted2 = _costs.predict(planned_prog, dp=1,
                                        nominal_batch=batch)
        else:
            census2 = exe2.memory_census(feed=feed)
            predicted2 = exe2.cost_report(nominal_batch=batch)
        reduction = 1.0 - (census2["peak_bytes"]
                           / max(census["peak_bytes"], 1.0))
        # the satellite columns on the BASE row: planned peak + reduction
        row.set_measured(
            mem_planned_peak_bytes=round(census2["peak_bytes"]),
            mem_plan_reduction=round(reduction, 4),
            step_ms_planned=round(step2_s * 1e3, 3))
        prow = led.row(f"{model}_{mode}_planned", model=model, mode=mode,
                       batch_size=batch, devices=ndev, dp=dp,
                       memory_plan=True)
        prow.set_prediction(predicted2)
        prow.set_memory_census(census2)
        prow.set_measured(
            step_ms=round(step2_s * 1e3, 3), iters=iters,
            temp_source=census2["xla"]["temp_source"],
            mem_planned_peak_bytes=round(census2["peak_bytes"]),
            mem_plan_reduction=round(reduction, 4))
        # the r17 identity must STILL hold on the planned cell, and the
        # reduction must land in the named transient category at a
        # planned step within the band
        prow.set_measured(step_time_noise_iqr_rel=round(noise_rel, 4))
        prow.check_memory_identity(residual_frac=0.10)
        prow.check_plan_reduction(
            {"memory": census, "step_ms": round(step_s * 1e3, 3)},
            min_reduction=0.0, time_band=time_band)
        print(json.dumps({"cell": prow.name,
                          "reduction": round(reduction, 4),
                          "time_ratio": round(step2_s / step_s, 4),
                          "ok": prow.ok}), flush=True)
        assert prow.ok, [c for c in prow.checks if not c["ok"]]

    print(json.dumps({"cell": row.name, "residual": rec, "ok": row.ok}),
          flush=True)
    assert row.ok, [c for c in row.checks if not c["ok"]]


def live_surface_smoke(led, trace_path):
    """ptpu_mfu + the memory watermark counters must be visible on BOTH
    live surfaces: one /metrics scrape of a serving EngineServer and one
    Chrome trace export (the r17 acceptance criterion)."""
    from paddle_tpu.observability import memory as obs_memory
    from paddle_tpu.observability import tracing
    from paddle_tpu.serving_engine import (ContinuousBatchingEngine,
                                           EngineClient, EngineServer,
                                           scrape_healthz, scrape_metrics)

    eng = ContinuousBatchingEngine(n_slots=2, vocab=64, max_len=16,
                                   d_model=32, d_inner=64, num_heads=4,
                                   num_layers=2)
    with EngineServer(eng) as srv:
        host, port = srv.address
        with EngineClient(host, port) as c:
            c.send_gen([3], max_new=2, request_id="bench-mem")
            c.recv_done()
        text = scrape_metrics(*srv.metrics_address)
        health = scrape_healthz(*srv.metrics_address)

    checks = []

    def chk(what, ok, detail):
        checks.append({"what": what, "ok": bool(ok), "detail": detail})
        assert ok, (what, detail)

    for series in ("ptpu_mfu", "ptpu_memory_device_state_bytes",
                   "ptpu_memory_executor_temp_bytes",
                   "ptpu_memory_kv_cache_bytes",
                   "ptpu_memory_host_staging_bytes",
                   'ptpu_memory_watermark_bytes{channel="kv_cache_bytes"}'):
        chk(f"scrape has {series}", series in text, "GET /metrics")
    kv = float([ln.split()[-1] for ln in text.splitlines()
                if ln.startswith("ptpu_engine_kv_cache_bytes")][0])
    wm = float([ln.split()[-1] for ln in text.splitlines()
                if ln.startswith("ptpu_memory_kv_cache_bytes")][0])
    chk("kv watermark == engine kv census", kv == wm and kv > 0,
        {"engine": kv, "watermark": wm})
    chk("healthz carries the memory board",
        "memory" in health and "kv_cache_bytes" in health["memory"]
        and health["memory"]["kv_cache_bytes"]["current"] == kv,
        health.get("memory"))

    tracing.export_chrome_trace(trace_path)
    with open(trace_path) as f:
        events = json.load(f)["traceEvents"]
    counters = [e for e in events if e.get("ph") == "C"]
    names = {e["name"] for e in counters}
    chk("trace export has memory counter events",
        any(n.startswith("memory/") for n in names),
        sorted(names)[:8])
    chk("trace export has the mfu counter", "memory/mfu" in names,
        sorted(names)[:8])
    row = led.row("live_surfaces", trace=os.path.basename(trace_path))
    row.set_measured(kv_cache_bytes=kv, counter_events=len(counters),
                     counter_names=sorted(names))
    for c in checks:
        row._check(c["what"], True, c["detail"], "present", c["ok"])


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out", default=os.path.join(REPO,
                                                 "BENCH_MEM_r17.json"))
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--cells", default="",
                   help="comma-separated model:mode subset (CI smoke "
                        "uses mnist:dp2); default = all cells")
    p.add_argument("--plan", action="store_true",
                   help="the r18 memory-plan matrix (PLAN_CELLS): run "
                        "every cell's memory-planned twin next to the "
                        "unplanned baseline, commit the measured census "
                        "delta + step-time ratio + identity on the "
                        "planned cell (BENCH_MEMPLAN_r18.json)")
    p.add_argument("--time_frac", type=float, default=0.02,
                   help="--plan: the step-time budget recorded on each "
                        "plan, as a fraction of the MEASURED unplanned "
                        "step (gates candidates only under the "
                        "mandated-recompute mode; the default CSE-able "
                        "plans are held to the bar by the measured "
                        "plan_step_time_band check instead)")
    p.add_argument("--skip_live", action="store_true",
                   help="skip the serving-engine live-surface smoke")
    p.add_argument("--trace_out", default="/tmp/bench_mem_trace.json")
    args = p.parse_args()

    import jax
    from paddle_tpu.observability.ledger import CostLedger

    table = PLAN_CELLS if args.plan else CELLS
    cells = table
    if args.cells:
        want = {tuple(c.split(":")) for c in args.cells.split(",")}
        cells = [c for c in table if c in want]
        assert cells, f"no cell matches {args.cells!r} (known: {table})"

    led = CostLedger("r18-memplan" if args.plan else "r17", meta={
        "mesh": "virtual CPU x8 (byte/category checks are exact "
                "properties of the compiled executable and transfer to "
                "TPU unchanged; ms/MFU numbers are CPU-mesh)",
        "identity": "every measured per-device byte attributed to a "
                    "predicted category or a NAMED residual bucket; "
                    "exact on state/feed categories, unattributed "
                    "<= 10% of measured peak"
                    + ("; planned cells additionally reconcile their "
                       "census against the unplanned twin "
                       "(check_plan_reduction: state/feeds invariant, "
                       "reduction fully in the named transient "
                       "category, step within the band)"
                       if args.plan else ""),
        "devices": [str(d) for d in jax.devices()[:2]],
    })
    for model, mode in cells:
        run_cell(led, model, mode,
                 batch=PLAN_BATCH.get(model, 16),
                 iters=(max(args.iters, 20) if args.plan else args.iters),
                 plan=args.plan, time_frac=args.time_frac)
    if not args.skip_live and not args.plan:
        live_surface_smoke(led, args.trace_out)
    path = led.write(args.out)
    print(json.dumps({"artifact": path, "ok": led.ok,
                      "cells": len(led.rows)}), flush=True)
    assert led.ok


if __name__ == "__main__":
    main()
