"""BENCH_MEM: the r17 measured-vs-predicted MEMORY ledger artifact.

Closes the loop the r12 ledger left open: `costs.predict()["memory"]`
was a pure static estimate with no measured side. Each cell below runs
one program x parallel config on the virtual 8-device CPU mesh and
commits the ACCOUNTING IDENTITY (observability/ledger.py
check_memory_identity):

  predicted  costs.predict over the program AS RUN — per-device state/
             feed/transient byte categories from declared shapes +
             placement markers (costs.memory_categories)
  measured   observability.memory.device_memory_census — per-device
             state bytes from the ACTUAL device arrays, the XLA
             executable's argument/output/temp/alias figures
             (memory_analysis; HLO liveness-walk fallback documented in
             `temp_source`), and a live-array sweep
  checks     per-category bytes EXACT (params / optimizer_state /
             ef_residual / other_state / feeds), the category walk
             re-derives XLA's own argument figure within 64 bytes, and
             unattributed measured bytes <= 10% of the measured peak

plus the MFU sensor (`costs.mfu` over the blocked-measured step time)
per cell, and a LIVE-SURFACE smoke: one /metrics scrape and one Chrome
trace export must both carry the `ptpu_memory_*` / `ptpu_mfu` series
and the `memory/*` counter events.

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python tools/bench_mem.py --out BENCH_MEM_r17.json

Byte/category checks are exact properties of the compiled executable
and transfer to TPU unchanged; ms/MFU numbers are CPU-mesh.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _build_mnist_mlp(rng, batch):
    import paddle_tpu as pt
    from paddle_tpu import layers
    x = layers.data("x", shape=[64])
    label = layers.data("label", shape=[1], dtype="int64")
    h = layers.fc(x, size=128, act="relu")
    h2 = layers.fc(h, size=64, act="relu")
    loss = layers.mean(layers.softmax_with_cross_entropy(
        layers.fc(h2, size=10), label))
    pt.optimizer.MomentumOptimizer(0.1, momentum=0.9).minimize(loss)
    feed = {"x": rng.rand(batch, 64).astype("float32"),
            "label": rng.randint(0, 10, (batch, 1)).astype("int64")}
    return loss, feed


def _build_transformer_lm(rng, batch, tp=0):
    import paddle_tpu as pt
    from paddle_tpu.models import transformer
    T = 8
    loss, _ = transformer.transformer_lm(
        vocab=64, max_len=T, d_model=32, d_inner=64, num_heads=4,
        num_layers=2, dropout=0.0, mean_loss=True)
    if tp > 1:
        from paddle_tpu.parallel import annotate_tp
        assert annotate_tp(), "annotate_tp matched nothing"
    pt.optimizer.AdamOptimizer(1e-3).minimize(loss)
    feed = {"tokens": rng.randint(0, 64, (batch, T)).astype("int64"),
            "tokens@SEQLEN": np.full((batch,), T, "int32"),
            "targets": rng.randint(0, 64, (batch, T)).astype("int64")}
    return loss, feed


#: cell -> (model, mode); modes cover {plain, dp2, dp2_ef, pp2,
#: dp2xpp2, tp2} — ISSUE 13 asks >= 4 program x parallel-config cells
CELLS = [
    ("mnist", "plain"),
    ("mnist", "dp2"),
    ("mnist", "dp2_ef"),
    ("mnist", "pp2"),
    ("mnist", "dp2xpp2"),
    ("transformer_lm", "plain"),
    ("transformer_lm", "dp2"),
    ("transformer_lm", "tp2"),
]


def run_cell(led, model, mode, batch, iters):
    import jax
    import paddle_tpu as pt
    from paddle_tpu.core import flags as _flags
    from paddle_tpu.framework import costs as _costs
    from paddle_tpu.parallel import ParallelExecutor
    from paddle_tpu.parallel.mesh import DeviceMesh
    from paddle_tpu.parallel.strategy import BuildStrategy, ReduceStrategy

    _flags.set_flag("use_bf16_matmul", False)
    rng = np.random.RandomState(7)
    pt.reset_default_programs()
    pt.reset_global_scope()
    tp = 2 if mode == "tp2" else 0
    with pt.core.unique_name.guard():
        if model == "mnist":
            loss, feed = _build_mnist_mlp(rng, batch)
        else:
            loss, feed = _build_transformer_lm(rng, batch, tp=tp)

    bst = BuildStrategy()
    if mode != "pp2":   # a pp-only mesh has no dp axis for explicit comm
        bst.reduce_strategy = ReduceStrategy.ReduceScatter
    mesh = None
    dp = 1
    if mode in ("dp2", "dp2_ef"):
        mesh = DeviceMesh(jax.devices()[:2], {"dp": 2})
        dp = 2
        if mode == "dp2_ef":
            bst.quant_comm = "int8"
            bst.comm_error_feedback = True
    elif mode == "pp2":
        bst.pipeline_stages = 2
        bst.num_microbatches = 4
        bst.pipeline_schedule = "1f1b"
        mesh = DeviceMesh(jax.devices()[:2], {"pp": 2})
    elif mode == "dp2xpp2":
        bst.pipeline_stages = 2
        bst.num_microbatches = 4
        bst.pipeline_schedule = "1f1b"
        mesh = DeviceMesh(jax.devices()[:4], {"dp": 2, "pp": 2})
        dp = 2
    elif mode == "tp2":
        mesh = DeviceMesh(jax.devices()[:2], {"dp": 1, "tp": 2})

    if mode == "plain":
        exe = pt.Executor()
        pt.Executor().run(pt.default_startup_program())
        run = lambda: exe.run(feed=feed, fetch_list=[loss],  # noqa: E731
                              return_numpy=False)
    else:
        exe = ParallelExecutor(loss_name=loss.name, build_strategy=bst,
                               mesh=mesh)
        pt.Executor().run(pt.default_startup_program())
        run = lambda: exe.run(feed=feed, fetch_list=[loss],  # noqa: E731
                              return_numpy=False)

    out = run()                                   # compile + warm
    jax.block_until_ready(out)
    t0 = time.time()
    for _ in range(iters):
        out = run()
    jax.block_until_ready(out)
    step_s = (time.time() - t0) / iters

    if mode == "plain":
        predicted = _costs.predict(pt.default_main_program(), dp=1,
                                   nominal_batch=batch)
    else:
        predicted = exe.cost_report(nominal_batch=batch)
    census = exe.memory_census(feed=feed)

    ndev = max(1, int(getattr(exe, "device_count", 1)))
    flops = predicted["compute"]["flops"]
    cell_mfu = _costs.mfu(flops / ndev, step_s)

    row = led.row(f"{model}_{mode}", model=model, mode=mode,
                  batch_size=batch, devices=ndev, dp=dp)
    row.set_prediction(predicted)
    row.set_memory_census(census)
    row.set_measured(step_ms=round(step_s * 1e3, 3), iters=iters,
                     mfu=cell_mfu,
                     temp_source=census["xla"]["temp_source"])
    rec = row.check_memory_identity(residual_frac=0.10)
    row._check("mfu_positive", ">0", round(cell_mfu, 10), ">0",
               cell_mfu > 0)
    print(json.dumps({"cell": row.name, "residual": rec, "ok": row.ok}),
          flush=True)
    assert row.ok, [c for c in row.checks if not c["ok"]]


def live_surface_smoke(led, trace_path):
    """ptpu_mfu + the memory watermark counters must be visible on BOTH
    live surfaces: one /metrics scrape of a serving EngineServer and one
    Chrome trace export (the r17 acceptance criterion)."""
    from paddle_tpu.observability import memory as obs_memory
    from paddle_tpu.observability import tracing
    from paddle_tpu.serving_engine import (ContinuousBatchingEngine,
                                           EngineClient, EngineServer,
                                           scrape_healthz, scrape_metrics)

    eng = ContinuousBatchingEngine(n_slots=2, vocab=64, max_len=16,
                                   d_model=32, d_inner=64, num_heads=4,
                                   num_layers=2)
    with EngineServer(eng) as srv:
        host, port = srv.address
        with EngineClient(host, port) as c:
            c.send_gen([3], max_new=2, request_id="bench-mem")
            c.recv_done()
        text = scrape_metrics(*srv.metrics_address)
        health = scrape_healthz(*srv.metrics_address)

    checks = []

    def chk(what, ok, detail):
        checks.append({"what": what, "ok": bool(ok), "detail": detail})
        assert ok, (what, detail)

    for series in ("ptpu_mfu", "ptpu_memory_device_state_bytes",
                   "ptpu_memory_executor_temp_bytes",
                   "ptpu_memory_kv_cache_bytes",
                   "ptpu_memory_host_staging_bytes",
                   'ptpu_memory_watermark_bytes{channel="kv_cache_bytes"}'):
        chk(f"scrape has {series}", series in text, "GET /metrics")
    kv = float([ln.split()[-1] for ln in text.splitlines()
                if ln.startswith("ptpu_engine_kv_cache_bytes")][0])
    wm = float([ln.split()[-1] for ln in text.splitlines()
                if ln.startswith("ptpu_memory_kv_cache_bytes")][0])
    chk("kv watermark == engine kv census", kv == wm and kv > 0,
        {"engine": kv, "watermark": wm})
    chk("healthz carries the memory board",
        "memory" in health and "kv_cache_bytes" in health["memory"]
        and health["memory"]["kv_cache_bytes"]["current"] == kv,
        health.get("memory"))

    tracing.export_chrome_trace(trace_path)
    with open(trace_path) as f:
        events = json.load(f)["traceEvents"]
    counters = [e for e in events if e.get("ph") == "C"]
    names = {e["name"] for e in counters}
    chk("trace export has memory counter events",
        any(n.startswith("memory/") for n in names),
        sorted(names)[:8])
    chk("trace export has the mfu counter", "memory/mfu" in names,
        sorted(names)[:8])
    row = led.row("live_surfaces", trace=os.path.basename(trace_path))
    row.set_measured(kv_cache_bytes=kv, counter_events=len(counters),
                     counter_names=sorted(names))
    for c in checks:
        row._check(c["what"], True, c["detail"], "present", c["ok"])


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out", default=os.path.join(REPO,
                                                 "BENCH_MEM_r17.json"))
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--cells", default="",
                   help="comma-separated model:mode subset (CI smoke "
                        "uses mnist:dp2); default = all cells")
    p.add_argument("--skip_live", action="store_true",
                   help="skip the serving-engine live-surface smoke")
    p.add_argument("--trace_out", default="/tmp/bench_mem_trace.json")
    args = p.parse_args()

    import jax
    from paddle_tpu.observability.ledger import CostLedger

    cells = CELLS
    if args.cells:
        want = {tuple(c.split(":")) for c in args.cells.split(",")}
        cells = [c for c in CELLS if c in want]
        assert cells, f"no cell matches {args.cells!r} (known: {CELLS})"

    led = CostLedger("r17", meta={
        "mesh": "virtual CPU x8 (byte/category checks are exact "
                "properties of the compiled executable and transfer to "
                "TPU unchanged; ms/MFU numbers are CPU-mesh)",
        "identity": "every measured per-device byte attributed to a "
                    "predicted category or a NAMED residual bucket; "
                    "exact on state/feed categories, unattributed "
                    "<= 10% of measured peak",
        "devices": [str(d) for d in jax.devices()[:2]],
    })
    for model, mode in cells:
        run_cell(led, model, mode, batch=16, iters=args.iters)
    if not args.skip_live:
        live_surface_smoke(led, args.trace_out)
    path = led.write(args.out)
    print(json.dumps({"artifact": path, "ok": led.ok,
                      "cells": len(led.rows)}), flush=True)
    assert led.ok


if __name__ == "__main__":
    main()
