#!/bin/bash
# CI driver (≙ reference paddle/scripts/paddle_build.sh: build + test +
# API check + benchmark smoke). Runs on the virtual 8-device CPU mesh.
#
#   tools/run_ci.sh          full tier (suite measured at ~40 min on this
#                            2-core box single-process — budget an hour)
#   tools/run_ci.sh quick    smoke tier (~5 min): build + API check +
#                            `-m quick`-marked tests + bench smoke
set -e
cd "$(dirname "$0")/.."
TIER="${1:-full}"

echo "== build native runtime =="
PTPU_BUILD_PREDICT=1 sh paddle_tpu/native/build.sh || \
    sh paddle_tpu/native/build.sh   # predictor needs TF libs; lib alone if absent

echo "== API surface check =="
JAX_PLATFORMS=cpu python tools/print_signatures.py | sort > /tmp/api_current.txt
sort API.spec > /tmp/api_golden.txt
diff /tmp/api_golden.txt /tmp/api_current.txt || {
    echo "API surface drifted — review and run tools/print_signatures.py --update"; exit 1; }

echo "== static program lint (analyzer over the flagship builders) =="
# whole-program shape/dtype inference + structural/parallel/dataflow
# verification (framework/analysis.py + framework/dataflow.py) over the
# flagship builders AND the serving-engine programs; exit 1 on any
# error-severity diagnostic. docs/static_analysis.md has the catalog.
JAX_PLATFORMS=cpu python tools/lint_program.py --model mnist
JAX_PLATFORMS=cpu python tools/lint_program.py --model transformer_lm
# the serving path: the engine's compiled decode tick + the prefill/
# generate program must be analyzer-clean too (docs/serving.md)
JAX_PLATFORMS=cpu python tools/lint_program.py \
    --model transformer_lm_decode_tick
JAX_PLATFORMS=cpu python tools/lint_program.py \
    --model transformer_lm_paged_decode_tick
JAX_PLATFORMS=cpu python tools/lint_program.py \
    --model transformer_lm_quant_decode_tick
# the r22 speculative-decoding programs: draft tick + both verify
# forwards (serving/speculative.py builds exactly these shapes)
JAX_PLATFORMS=cpu python tools/lint_program.py \
    --model transformer_lm_draft_tick
JAX_PLATFORMS=cpu python tools/lint_program.py \
    --model transformer_lm_spec_verify_tick
JAX_PLATFORMS=cpu python tools/lint_program.py \
    --model transformer_lm_paged_spec_verify_tick
JAX_PLATFORMS=cpu python tools/lint_program.py --model transformer_lm_prefill
# tp lint: tp-annotated transformer through tp_shard_pass at tp=2; prints
# the propagated sharding-spec table and fails on any propagation conflict
# (docs/tensor_parallel.md has the rule catalog)
JAX_PLATFORMS=cpu python tools/lint_program.py --model transformer_lm_tp \
    --tp 2

if [ "$TIER" != "quick" ]; then
    echo "== lint-all sweep: every builder x {plain, dp2, pp2, tp2} =="
    # the zero-false-positive acceptance gate: every model builder, under
    # every parallelism rewrite its gates admit, must produce zero
    # error-severity diagnostics. --json is the contract (machine-readable
    # code/severity/op_loc rows; documented exit codes in
    # tools/lint_program.py) — no table scraping. Pass gates rejecting a
    # (model, config) pair are expected sweep noise (--allow_gate_rejects).
    # the r18 planned variants ride the same sweep: every (model, config)
    # pair is ALSO linted through memory_plan_pass — the planner's
    # scheduling/coloring/remat must introduce zero error diagnostics on
    # every program the detectors accept unplanned
    rm -f /tmp/lint_sweep_*.json
    i=0
    for flags in "" "--dp 2" "--pipeline_stages 2 --num_microbatches 4" \
                 "--tp 2" "--memory_plan" "--dp 2 --memory_plan" \
                 "--pipeline_stages 2 --num_microbatches 4 --memory_plan" \
                 "--tp 2 --memory_plan"; do
        # don't let set -e kill the sweep on a lint exit(1): the Python
        # aggregator below owns the gating AND prints which model/config/
        # code failed (a hard crash leaves truncated JSON, which the
        # aggregator's json.load turns into a failure too)
        JAX_PLATFORMS=cpu python tools/lint_program.py --all --json \
            --allow_gate_rejects $flags > /tmp/lint_sweep_$i.json || true
        i=$((i+1))
    done
    python - <<'PY'
import glob, json
rows = [r for f in sorted(glob.glob("/tmp/lint_sweep_*.json"))
        for r in json.load(open(f))]
bad = [r for r in rows if r["errors"]]
gated = [r for r in rows if r["gate_rejected"]]
for r in bad:
    for d in r["diagnostics"]:
        if d["severity"] == "error":
            print(f"{r['model']} {r['config']}: [{d['code']}] "
                  f"{d['loc']}: {d['message']}")
assert not bad, f"{len(bad)} builder/config pair(s) with error diagnostics"
print(f"lint-all sweep OK: {len(rows) - len(gated)} program(s) clean, "
      f"{len(gated)} gate-skipped across {len(rows)} (model, config) pairs")
PY
fi

if [ "$TIER" = "quick" ]; then
    echo "== quick test tier (~5 min) =="
    # the fusion numeric-parity tests (tests/test_fusion.py) ride this
    # tier via their `quick` marks — the fuse passes are default-on, so
    # every smoke must see them verified. PTPU_VERIFY_PASSES=1 keeps the
    # pass sanitizer active, so every pass test doubles as a sanitizer
    # test (it is also the default; the env pins it).
    PTPU_VERIFY_PASSES=1 \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python -m pytest tests/ -q -x -m quick
else
    echo "== full test pyramid (~29 min on 2 cores with -n 2; measured) =="
    # tier-1 selection: everything but the slow-marked A/B bench smokes
    PTPU_VERIFY_PASSES=1 \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python -m pytest tests/ -q -n 2 --dist load -m 'not slow'
fi

echo "== benchmark smoke =="
JAX_PLATFORMS=cpu python tools/benchmark.py --model mnist --batch_size 8 \
    --iters 3 --warmup 1

echo "== dp-comm smoke (reduce-scatter + quantized collectives) =="
# the explicit gradient pipeline end to end on the 8-virtual-device mesh:
# reduce-scatter mode must leave no gradient all-reduce in the compiled
# step, quantized mode must put int8 on the wire, and both must train.
# (A REAL 2-process world needs jaxlib >= 0.5 — the CPU backend below
# that cannot run multi-process collectives; tests/test_dist_multiproc.py
# carries the same skip. This smoke pins the structure, which is
# process-count-invariant.)
XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
python - <<'PY'
import numpy as np, jax
import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.parallel import ParallelExecutor
from paddle_tpu.parallel.strategy import BuildStrategy, ReduceStrategy
import sys, os
sys.path.insert(0, "tools")
from probe_common import collective_census

for quant in ("", "int8"):
    pt.reset_default_programs(); pt.reset_global_scope()
    with pt.core.unique_name.guard():
        x = layers.data("x", shape=[64])
        label = layers.data("label", shape=[1], dtype="int64")
        h = layers.fc(x, size=128, act="relu")
        loss = layers.mean(layers.softmax_with_cross_entropy(
            layers.fc(h, size=10), label))
        pt.optimizer.MomentumOptimizer(0.1, momentum=0.9).minimize(loss)
    bst = BuildStrategy(); bst.reduce_strategy = ReduceStrategy.ReduceScatter
    bst.quant_comm = quant; bst.comm_error_feedback = bool(quant)
    exe = ParallelExecutor(loss_name=loss.name, build_strategy=bst)
    pt.Executor().run(pt.default_startup_program())
    rng = np.random.RandomState(0)
    feed = {"x": rng.rand(32, 64).astype("float32"),
            "label": rng.randint(0, 10, (32, 1)).astype("int64")}
    l0 = float(exe.run(feed=feed, fetch_list=[loss])[0])
    l1 = float(exe.run(feed=feed, fetch_list=[loss])[0])
    assert l1 < l0, (quant, l0, l1)          # it actually trains
    import jax.numpy as jnp
    cs = list(exe._cache.values())[-1]
    scope = pt.global_scope()
    hlo = cs.fn.lower(tuple(jnp.asarray(feed[n]) for n in cs.feed_names),
                      tuple(scope.get(n) for n in cs.ro_names),
                      tuple(scope.get(n) for n in cs.rw_names),
                      np.uint32(0)).compile().as_text()
    census = collective_census(hlo)
    assert all(b <= 64 for b, _ in census.get("all-reduce", [])), \
        "gradient all-reduce leaked into reduce-scatter mode"
    if quant:
        assert any("s8[" in l for v in census.values() for _, l in v), \
            "quantized mode has no int8 on the wire"
print("dp-comm smoke OK")
PY

echo "== tensor-parallel smoke (tp2 parity through tp_shard_pass) =="
# the static sharding subsystem end to end: annotate_tp + tp_shard_pass +
# the full-manual shard_map executor must reproduce the single-device
# fixed-seed loss curve on a dp1 x tp2 mesh in ReduceScatter mode
# (f32 matmuls: splitting a bf16 contraction changes its rounding).
XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
python - <<'PY'
import numpy as np, jax
import paddle_tpu as pt
from paddle_tpu.core import flags
from paddle_tpu.parallel import ParallelExecutor, annotate_tp
from paddle_tpu.parallel.mesh import DeviceMesh
from paddle_tpu.parallel.strategy import BuildStrategy, ReduceStrategy

flags.set_flag("use_bf16_matmul", False)

def build():
    from paddle_tpu.models import transformer
    loss, _ = transformer.transformer_lm(
        vocab=64, max_len=8, d_model=32, d_inner=64, num_heads=4,
        num_layers=2, mean_loss=True)
    pt.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    return loss

rng = np.random.RandomState(7)
feeds = [{"tokens": rng.randint(0, 64, (8, 8)).astype("int64"),
          "tokens@SEQLEN": np.full((8,), 8, "int32"),
          "targets": rng.randint(0, 64, (8, 8)).astype("int64")}
         for _ in range(3)]
pt.reset_default_programs(); pt.reset_global_scope()
with pt.core.unique_name.guard():
    loss = build()
exe = pt.Executor(); exe.run(pt.default_startup_program())
base = [float(exe.run(feed=f, fetch_list=[loss])[0]) for f in feeds]
pt.reset_default_programs(); pt.reset_global_scope()
with pt.core.unique_name.guard():
    loss = build()
assert annotate_tp()
bst = BuildStrategy(); bst.reduce_strategy = ReduceStrategy.ReduceScatter
mesh = DeviceMesh(jax.devices()[:2], {"dp": 1, "tp": 2})
pexe = ParallelExecutor(loss_name=loss.name, mesh=mesh,
                        build_strategy=bst)
pt.Executor().run(pt.default_startup_program())
got = [float(pexe.run(feed=f, fetch_list=[loss])[0]) for f in feeds]
assert max(abs(a - b) for a, b in zip(base, got)) <= 1e-5, (base, got)
prog = pexe._prepare_program(pt.default_main_program(), pt.global_scope())
assert getattr(prog, "_tp_applied", False)
print("tensor-parallel smoke OK")
PY

echo "== pipeline-parallel smoke (gpipe + 1f1b parity, pp=2, M=4) =="
# the program-level pipeline executor end to end: partition pass + both
# schedules must reproduce the single-device fixed-seed loss curve, and
# the compiled step must carry exactly one boundary-activation + one
# boundary-gradient collective-permute per tick.
XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
python - <<'PY'
import numpy as np, jax
import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.parallel import ParallelExecutor
from paddle_tpu.parallel.mesh import DeviceMesh
from paddle_tpu.parallel.strategy import BuildStrategy
import sys
sys.path.insert(0, "tools")
from probe_common import collective_census

def build():
    x = layers.data("x", shape=[32])
    label = layers.data("label", shape=[1], dtype="int64")
    h = layers.fc(x, size=64, act="relu")
    h = layers.fc(h, size=64, act="relu")
    loss = layers.mean(layers.softmax_with_cross_entropy(
        layers.fc(h, size=10), label))
    pt.optimizer.MomentumOptimizer(0.1, momentum=0.9).minimize(loss)
    return loss

rng = np.random.RandomState(0)
feeds = [{"x": np.random.RandomState(50 + i).rand(16, 32).astype("f4"),
          "label": np.random.RandomState(60 + i)
          .randint(0, 10, (16, 1)).astype("i8")} for i in range(3)]
pt.reset_default_programs(); pt.reset_global_scope()
with pt.core.unique_name.guard():
    loss = build()
exe = pt.Executor(); exe.run(pt.default_startup_program())
base = [float(exe.run(feed=f, fetch_list=[loss])[0]) for f in feeds]
for sched in ("gpipe", "1f1b"):
    pt.reset_default_programs(); pt.reset_global_scope()
    with pt.core.unique_name.guard():
        loss = build()
    bst = BuildStrategy(pipeline_stages=2, num_microbatches=4,
                        pipeline_schedule=sched)
    mesh = DeviceMesh(jax.devices()[:2], {"pp": 2})
    pexe = ParallelExecutor(loss_name=loss.name, mesh=mesh,
                            build_strategy=bst)
    pt.Executor().run(pt.default_startup_program())
    got = [float(pexe.run(feed=f, fetch_list=[loss])[0]) for f in feeds]
    assert max(abs(a - b) for a, b in zip(base, got)) <= 1e-5, (sched,
                                                                base, got)
    import jax.numpy as jnp
    cs = list(pexe._cache.values())[-1]
    scope = pt.global_scope()
    hlo = cs.fn.lower(tuple(jnp.asarray(feeds[-1][n])
                            for n in cs.feed_names),
                      tuple(scope.get(n) for n in cs.ro_names),
                      tuple(scope.get(n) for n in cs.rw_names),
                      np.uint32(0)).compile().as_text()
    census = collective_census(hlo)
    n_perm = len(census.get("collective-permute", []))
    assert n_perm == 2, (sched, n_perm)
print("pipeline smoke OK")
PY

echo "== observability smoke (spans + ledger + /metrics) =="
# the r12 layer end to end: a traced 3-step mnist run must record the
# executor's compile/step/feed_fetch spans, the cost ledger's predicted
# wire bytes must equal the HLO census EXACTLY on a dp2 reduce-scatter
# step, and one Prometheus scrape of a live EngineServer must carry the
# serving telemetry (docs/observability.md).
XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
python - <<'PY'
import numpy as np, jax
import jax.numpy as jnp
import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.framework.costs import collective_census
from paddle_tpu.observability import tracing
from paddle_tpu.observability.ledger import CostLedger
from paddle_tpu.parallel import ParallelExecutor
from paddle_tpu.parallel.strategy import BuildStrategy, ReduceStrategy
from paddle_tpu.parallel.mesh import DeviceMesh

pt.reset_default_programs(); pt.reset_global_scope()
with pt.core.unique_name.guard():
    x = layers.data("x", shape=[64])
    label = layers.data("label", shape=[1], dtype="int64")
    h = layers.fc(x, size=128, act="relu")
    loss = layers.mean(layers.softmax_with_cross_entropy(
        layers.fc(h, size=10), label))
    pt.optimizer.MomentumOptimizer(0.1, momentum=0.9).minimize(loss)
bst = BuildStrategy(); bst.reduce_strategy = ReduceStrategy.ReduceScatter
mesh = DeviceMesh(jax.devices()[:2], {"dp": 2})
exe = ParallelExecutor(loss_name=loss.name, build_strategy=bst, mesh=mesh)
pt.Executor().run(pt.default_startup_program())
rng = np.random.RandomState(0)
feed = {"x": rng.rand(16, 64).astype("float32"),
        "label": rng.randint(0, 10, (16, 1)).astype("int64")}
mark = tracing.mark()
for _ in range(3):                                   # traced 3-step run
    exe.run(feed=feed, fetch_list=[loss])
kinds = {(s.kind, s.name) for s in tracing.spans_since(mark)}
assert ("step", "executor/run") in kinds, kinds
assert ("feed_fetch", "executor/feed") in kinds, kinds

cs = list(exe._cache.values())[-1]
scope = pt.global_scope()
hlo = cs.fn.lower(tuple(jnp.asarray(feed[n]) for n in cs.feed_names),
                  tuple(scope.get(n) for n in cs.ro_names),
                  tuple(scope.get(n) for n in cs.rw_names),
                  np.uint32(0)).compile().as_text()
row = CostLedger("ci").row("mnist_dp2_rs")
row.set_prediction(exe.cost_report(nominal_batch=16))
row.set_census(collective_census(hlo), 2, min_bytes=8)
chk = row.check_wire_bytes_exact()
assert chk["ok"], chk                     # predicted == census, exactly

from paddle_tpu.serving_engine import (ContinuousBatchingEngine,
                                       EngineClient, EngineServer,
                                       scrape_healthz, scrape_metrics)
eng = ContinuousBatchingEngine(n_slots=2, vocab=100, max_len=16,
                               d_model=32, d_inner=64, num_heads=4,
                               num_layers=2)
with EngineServer(eng) as srv:
    host, port = srv.address
    with EngineClient(host, port) as c:
        c.send_gen([3], max_new=2, request_id="ci-req")
        c.recv_done()
    text = scrape_metrics(*srv.metrics_address)
    health = scrape_healthz(*srv.metrics_address)
assert "ptpu_engine_tokens_total 2" in text, text[:400]
assert "ptpu_engine_tick_latency_seconds_count" in text
# r16: the per-request latency decomposition series are on the scrape,
# for all four phases, and one scrape carries the checkpoint + training
# series too (unified registries)
for phase in ("queue_wait", "prefill", "decode", "transport"):
    assert f'ptpu_request_latency_seconds_count{{phase="{phase}"}}' \
        in text, phase
assert "ptpu_request_e2e_seconds_count" in text
assert "ptpu_ckpt_saves_total" in text and "ptpu_train_steps_total" in text
# r17: ONE scrape also carries the memory board + the MFU sensor
for series in ("ptpu_mfu", "ptpu_memory_device_state_bytes",
               "ptpu_memory_kv_cache_bytes",
               "ptpu_memory_watermark_bytes"):
    assert series in text, series
# r16: /healthz is live on the same listener
assert health["status"] == "serving", health
assert health["engine"]["last_tick_age_s"] is not None
assert health["checkpoints"]["pending_async"] == 0
# r17: /healthz embeds the same memory board the dossiers carry
assert health["memory"]["kv_cache_bytes"]["current"] > 0, health
print("observability smoke OK")
PY

echo "== memory-observability smoke (census + ledger identity + MFU) =="
# the r17 memory sensor end to end (docs/observability.md): a traced
# mnist dp2 step must reconcile its measured memory census against
# costs.predict's per-device categories under the accounting identity
# (state/feed categories EXACT, unattributed residual <= 10% of the
# measured peak), stamp the ptpu_memory_* watermarks + ptpu_mfu, and
# emit memory COUNTER events into the Chrome trace export. Then the
# BENCH_MEM artifact generator must run clean on the same cell.
XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
python - <<'PY'
import json, numpy as np, jax
import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.observability import memory as obs_memory
from paddle_tpu.observability import metrics as obs_metrics
from paddle_tpu.observability import tracing
from paddle_tpu.observability.ledger import CostLedger
from paddle_tpu.parallel import ParallelExecutor
from paddle_tpu.parallel.mesh import DeviceMesh
from paddle_tpu.parallel.strategy import BuildStrategy, ReduceStrategy

pt.reset_default_programs(); pt.reset_global_scope()
with pt.core.unique_name.guard():
    x = layers.data("x", shape=[64])
    label = layers.data("label", shape=[1], dtype="int64")
    h = layers.fc(x, size=128, act="relu")
    loss = layers.mean(layers.softmax_with_cross_entropy(
        layers.fc(h, size=10), label))
    pt.optimizer.MomentumOptimizer(0.1, momentum=0.9).minimize(loss)
bst = BuildStrategy(); bst.reduce_strategy = ReduceStrategy.ReduceScatter
exe = ParallelExecutor(loss_name=loss.name, build_strategy=bst,
                       mesh=DeviceMesh(jax.devices()[:2], {"dp": 2}))
pt.Executor().run(pt.default_startup_program())
rng = np.random.RandomState(0)
feed = {"x": rng.rand(16, 64).astype("float32"),
        "label": rng.randint(0, 10, (16, 1)).astype("int64")}
for _ in range(3):   # traced steps (first is the MFU warm-up window)
    exe.run(feed=feed, fetch_list=[loss])

row = CostLedger("ci").row("mnist_dp2_mem")
row.set_prediction(exe.cost_report(nominal_batch=16))
row.set_memory_census(exe.memory_census(feed=feed))
rec = row.check_memory_identity()
assert row.ok, [c for c in row.checks if not c["ok"]]

text = obs_metrics.default_registry().expose()
assert "ptpu_memory_device_state_bytes" in text
assert "ptpu_memory_executor_temp_bytes" in text
mfu = [l for l in text.splitlines() if l.startswith("ptpu_mfu ")][0]
assert float(mfu.split()[-1]) > 0, mfu

tracing.export_chrome_trace("/tmp/ptpu_mem_trace_ci.json")
evs = json.load(open("/tmp/ptpu_mem_trace_ci.json"))["traceEvents"]
counters = {e["name"] for e in evs if e.get("ph") == "C"}
assert any(n.startswith("memory/") for n in counters), counters
print("memory-observability smoke OK:", json.dumps(rec["buckets"]))
PY
rm -f /tmp/ptpu_mem_trace_ci.json /tmp/bench_mem_ci.json
XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
    python tools/bench_mem.py --out /tmp/bench_mem_ci.json --iters 2 \
    --cells mnist:dp2 --skip_live
python - <<'PY'
import json
doc = json.load(open("/tmp/bench_mem_ci.json"))
assert doc["ok"] and len(doc["rows"]) == 1, doc["ok"]
print("bench_mem smoke OK")
PY
rm -f /tmp/bench_mem_ci.json

echo "== memory-plan smoke (planner + detectors + measured reduction) =="
# the r18 static memory planner end to end (docs/static_analysis.md):
# (1) plan mnist dp2 through BuildStrategy.memory_plan — the sanitized
#     memory_plan_pass apply must stay lint-clean (the r13 buffer-reuse
#     detectors are the soundness gate) and the r17 ledger identity must
#     still hold on the planned cell; the mnist plan is a no-op by
#     SEARCH (nothing to free on the mlp) and its census must not
#     regress;
# (2) the activation-heavy transformer cell: the searched remat plan's
#     memory_census peak must land STRICTLY below the unplanned twin
#     (the measured matrix with step-time bands is BENCH_MEMPLAN_r18.json).
XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
    python - <<'PY'
import numpy as np, jax
import paddle_tpu as pt
from paddle_tpu.core import flags as _flags
from paddle_tpu.framework import analysis, costs as _costs
from paddle_tpu.framework.passes import get_pass
from paddle_tpu.observability.ledger import CostLedger
from paddle_tpu.parallel import ParallelExecutor
from paddle_tpu.parallel.mesh import DeviceMesh
from paddle_tpu.parallel.strategy import BuildStrategy, ReduceStrategy
_flags.set_flag("use_bf16_matmul", False)
led = CostLedger("ci-memplan")

# (1) mnist dp2 behind BuildStrategy.memory_plan
rng = np.random.RandomState(7)
from paddle_tpu import layers
x = layers.data("x", shape=[64]); label = layers.data("label", shape=[1], dtype="int64")
h = layers.fc(x, size=128, act="relu")
loss = layers.mean(layers.softmax_with_cross_entropy(layers.fc(h, size=10), label))
pt.optimizer.MomentumOptimizer(0.1, momentum=0.9).minimize(loss)
bst = BuildStrategy(); bst.reduce_strategy = ReduceStrategy.ReduceScatter
bst.memory_plan = True; bst.memory_plan_time_budget_s = 1.0
exe = ParallelExecutor(loss_name=loss.name, build_strategy=bst,
                       mesh=DeviceMesh(jax.devices()[:2], {"dp": 2}))
pt.Executor().run(pt.default_startup_program())
feed = {"x": rng.rand(16, 64).astype("float32"),
        "label": rng.randint(0, 10, (16, 1)).astype("int64")}
jax.block_until_ready(exe.run(feed=feed, fetch_list=[loss], return_numpy=False))
planned = exe.prepare_program()
assert getattr(planned, "_memory_plan_applied", False)
errs = [d for d in analysis.verify_program(planned) if d.severity == "error"]
assert not errs, errs
row = led.row("mnist_dp2_planned")
row.set_prediction(exe.cost_report(nominal_batch=16))
row.set_memory_census(exe.memory_census(feed=feed))
rec = row.check_memory_identity(residual_frac=0.10)
assert row.ok, [c for c in row.checks if not c["ok"]]

# (2) transformer: planned census peak strictly below unplanned
def build():
    pt.reset_default_programs(); pt.reset_global_scope()
    with pt.core.unique_name.guard():
        from paddle_tpu.models import transformer
        loss, _ = transformer.transformer_lm(
            vocab=128, max_len=32, d_model=64, d_inner=128, num_heads=4,
            num_layers=2, dropout=0.0, mean_loss=True)
        pt.optimizer.AdamOptimizer(1e-3).minimize(loss)
    r = np.random.RandomState(7)
    feed = {"tokens": r.randint(0, 128, (32, 32)).astype("int64"),
            "tokens@SEQLEN": np.full((32,), 32, "int32"),
            "targets": r.randint(0, 128, (32, 32)).astype("int64")}
    return loss, feed

def peak(prog, loss, feed):
    e = pt.Executor()
    pt.Executor().run(pt.default_startup_program())
    jax.block_until_ready(e.run(program=prog, feed=feed,
                                fetch_list=[loss], return_numpy=False))
    c = e.memory_census(feed=feed, program=prog)
    return c["peak_bytes"], c

loss, feed = build()
p_base, _ = peak(pt.default_main_program(), loss, feed)
loss, feed = build()
prog = get_pass("memory_plan_pass", nominal_batch=32,
                time_budget_s=1.0)(pt.default_main_program())
assert not [d for d in analysis.verify_program(prog)
            if d.severity == "error"]
p_plan, census = peak(prog, loss, feed)
assert p_plan < p_base, (p_plan, p_base)
prow = led.row("transformer_planned")
prow.set_prediction(_costs.predict(prog, dp=1, nominal_batch=32))
prow.set_memory_census(census)
prow.check_memory_identity(residual_frac=0.10)
assert prow.ok, [c for c in prow.checks if not c["ok"]]
import json
print("memory-plan smoke OK:", json.dumps({
    "transformer_peak_unplanned": round(p_base),
    "transformer_peak_planned": round(p_plan),
    "reduction": round(1 - p_plan / p_base, 4)}))
PY

echo "== auto-parallel smoke (planner choice: feasible + lint-clean + exact wire) =="
# the r19 auto-parallel planner end to end (docs/auto_parallel.md): plan
# mnist over a 4-device mesh; the chosen strategy must (1) be in the
# feasible set per the SAME compile-free gates the executor raises
# (costs.strategy_is_feasible), (2) leave the rewritten program
# analyzer-clean, and (3) balance its predicted per-step wire bytes
# against the executed HLO census EXACTLY (the r12 ledger discipline on
# a strategy the framework picked for itself). Then the lint surface:
# a feasible --strategy lints clean, an infeasible one exits 2 naming
# the reason.
XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
python - <<'PY'
import numpy as np, jax
import jax.numpy as jnp
import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.framework import analysis, auto_parallel, costs
from paddle_tpu.observability.ledger import CostLedger
from paddle_tpu.parallel import ParallelExecutor
from paddle_tpu.parallel.mesh import DeviceMesh

pt.reset_default_programs(); pt.reset_global_scope()
with pt.core.unique_name.guard():
    x = layers.data("x", shape=[64])
    label = layers.data("label", shape=[1], dtype="int64")
    h = layers.fc(x, size=128, act="relu")
    loss = layers.mean(layers.softmax_with_cross_entropy(
        layers.fc(h, size=10), label))
    pt.optimizer.MomentumOptimizer(0.1, momentum=0.9).minimize(loss)
prog = pt.default_main_program()
result = auto_parallel.plan(prog, 4, nominal_batch=16)
feas = costs.strategy_is_feasible(prog, result.strategy,
                                  mesh_axes=result.mesh_axes,
                                  nominal_batch=16)
assert feas.ok, feas.reasons                       # (1) feasible
errs = [d for d in analysis.verify_program(feas.program)
        if d.severity == "error"]
assert not errs, errs                              # (2) lint-clean

exe = ParallelExecutor(loss_name=loss.name, build_strategy=result.strategy,
                       mesh=DeviceMesh(jax.devices()[:4],
                                       result.mesh_axes))
pt.Executor().run(pt.default_startup_program())
rng = np.random.RandomState(0)
feed = {"x": rng.rand(16, 64).astype("float32"),
        "label": rng.randint(0, 10, (16, 1)).astype("int64")}
l0 = float(exe.run(feed=feed, fetch_list=[loss])[0])
l1 = float(exe.run(feed=feed, fetch_list=[loss])[0])
assert l1 < l0, (l0, l1)                           # it actually trains
cs = list(exe._cache.values())[-1]
scope = pt.global_scope()
hlo = cs.fn.lower(tuple(jnp.asarray(feed[n]) for n in cs.feed_names),
                  tuple(scope.get(n) for n in cs.ro_names),
                  tuple(scope.get(n) for n in cs.rw_names),
                  np.uint32(0)).compile().as_text()
row = CostLedger("ci").row("auto_parallel_choice")
row.set_prediction(exe.cost_report(nominal_batch=16))
row.set_census(costs.collective_census(hlo),
               exe.mesh.axis_size("dp"), min_bytes=8)
chk = row.check_wire_bytes_exact()
assert chk["ok"], chk                              # (3) exact balance
import json
print("auto-parallel smoke OK:", json.dumps({
    "chosen": result.point.describe(),
    "predicted_wire": chk["predicted"], "census": chk["measured"]}))
PY
JAX_PLATFORMS=cpu python tools/lint_program.py --model mnist \
    --strategy '{"dp": 2, "pp": 2, "microbatches": 4, "reduce": "reduce_scatter"}'
if JAX_PLATFORMS=cpu python tools/lint_program.py --model mnist \
    --strategy '{"dp": 2, "tp": 2, "reduce": "reduce_scatter"}'; then
    echo "lint accepted an INFEASIBLE strategy"; exit 1
fi

echo "== flight-recorder smoke (SIGKILL mid-barrier -> dossier + post-mortem) =="
# the distributed flight recorder end to end (observability/
# flight_recorder.py, docs/fault_tolerance.md): a 4-rank world-atomic
# child is SIGKILLed at a NON-CHIEF rank's ack phase via the existing
# PTPU_FAULT_INJECT crash_rank hook; the beacons written before the kill
# must name exactly that rank and phase, and the post-mortem synthesis
# must commit the verdict. (The merged-timeline path, trace_merge.py, is
# pinned by tests/test_observability.py.)
rm -rf /tmp/ptpu_flightrec_ci
XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
PTPU_FAULT_INJECT=crash_rank:2@ack \
    python tools/recovery_smoke.py --world-atomic-child --world 4 \
    --root /tmp/ptpu_flightrec_ci && { \
    echo "child survived a crash_rank directive"; exit 1; } || true
JAX_PLATFORMS=cpu python - <<'PY'
import json
from paddle_tpu.observability import flight_recorder as fr
d = "/tmp/ptpu_flightrec_ci/dossiers"
verdict = fr.analyze(d)
assert verdict["dead_rank"] == 2, verdict
assert verdict["dead_phase"] == "ack", verdict
assert verdict["cause"] == "crash_rank SIGKILL", verdict
pm = fr.write_post_mortem(d, incarnation=1)
doc = json.load(open(pm))
assert doc["dead_rank"] == 2 and doc["dead_phase"] == "ack"
print(f"flight-recorder smoke OK: {pm} names rank 2 @ ack")
PY
rm -rf /tmp/ptpu_flightrec_ci

echo "== recovery smoke (kill -9 mid-run, dp resize, fixed-seed parity) =="
# the elastic fault-tolerance runtime end to end (parallel/elastic.py,
# docs/fault_tolerance.md): a supervised child SIGKILLs itself mid-run and
# resumes BITWISE-exact from the latest committed snapshot; a second crashed
# run restarts with dp resized 2 -> 4 and matches the uninterrupted
# fixed-seed loss trajectory within the fp32 parity band; a kill DURING a
# snapshot write leaves only an uncommitted dir that restore skips. Then
# lint the restored program's sharded-state placement against the resized
# snapshot (exit 1 on any restore-* or verify_program diagnostic).
XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
    python tools/recovery_smoke.py --keep_root /tmp/ptpu_recovery_ci
JAX_PLATFORMS=cpu python tools/lint_program.py --model mnist \
    --optimizer momentum --dp 4 --restore_dir /tmp/ptpu_recovery_ci/b
rm -rf /tmp/ptpu_recovery_ci

echo "== multi-rank recovery (chief-commits barrier, kill -9 mid-barrier) =="
# the chief-commits multi-writer protocol end to end (parallel/elastic.py +
# parallel/process_world.py): training dp=4 snapshots through a 4-rank
# simulated world; a non-chief rank is SIGKILLed mid-barrier (nothing may
# commit) and the chief is SIGKILLed mid-COMMIT (a VISIBLE but uncommitted
# snapshot dir remains); both restarts resume from the last committed
# barrier snapshot with BITWISE fixed-seed loss parity vs the uninterrupted
# run. Then lint_program --restore_dir must ACCEPT every committed barrier
# snapshot (exit 0) and REJECT the uncommitted leftover (exit 1).
XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
    python tools/recovery_smoke.py --world 4 \
    --keep_root /tmp/ptpu_recovery_world_ci
JAX_PLATFORMS=cpu python tools/lint_program.py --model mnist \
    --optimizer momentum --dp 4 \
    --restore_dir /tmp/ptpu_recovery_world_ci/d
JAX_PLATFORMS=cpu python tools/lint_program.py --model mnist \
    --optimizer momentum --dp 4 \
    --restore_dir /tmp/ptpu_recovery_world_ci/e
uncommitted=$(ls -d /tmp/ptpu_recovery_world_ci/e/snapshot-* | while read d; do \
    [ ! -f "$d/COMMIT" ] && echo "$d"; done | head -1)
test -n "$uncommitted"
if JAX_PLATFORMS=cpu python tools/lint_program.py --model mnist \
    --optimizer momentum --restore_dir "$uncommitted"; then
    echo "lint accepted an UNCOMMITTED snapshot dir"; exit 1
fi
rm -rf /tmp/ptpu_recovery_world_ci

echo "== serving-engine smoke =="
# continuous-batching engine end to end: submit through the RPC server,
# decode over the slot cache, check a mid-batch join completes (fast:
# tiny LM, ~15 s including compile)
JAX_PLATFORMS=cpu python - <<'PY'
from paddle_tpu.serving_engine import (ContinuousBatchingEngine,
                                       EngineClient, EngineServer)
eng = ContinuousBatchingEngine(n_slots=2, vocab=100, max_len=16,
                               d_model=32, d_inner=64, num_heads=4,
                               num_layers=2)
with EngineServer(eng) as srv:
    host, port = srv.address
    with EngineClient(host, port) as c:
        long_tag = c.send_gen([3], max_new=8)
        short_tag = c.send_gen([5], max_new=2)      # joins mid-batch
        done = dict((t, toks) for t, toks, _ in
                    (c.recv_done(), c.recv_done()))
        assert len(done[long_tag]) == 8 and len(done[short_tag]) == 2
print("serving-engine smoke OK")
PY

echo "== paged-serving smoke (r20: block-table KV + prefix sharing) =="
# slot vs paged decode identity on a shared-prefix mix (same scope =
# same weights), prefix-cache hits on the second wave, and the census
# used-vs-reserved reconciliation (used + free == reserved, exactly)
JAX_PLATFORMS=cpu python - <<'PY'
import paddle_tpu as pt
from paddle_tpu.observability.memory import watermark_board
from paddle_tpu.serving import ContinuousBatchingEngine, PagedKVEngine
DIMS = dict(vocab=100, max_len=16, d_model=32, d_inner=64, num_heads=4,
            num_layers=2)
scope = pt.global_scope()
slot = ContinuousBatchingEngine(n_slots=3, scope=scope, **DIMS)
paged = PagedKVEngine(n_slots=3, block_size=4, scope=scope, **DIMS)
pre = [2, 7, 1, 9, 4, 8, 5, 6]
waves = [[pre + [3]], [pre + [11], pre + [12, 13], [6, 5, 4]]]
for wave in waves:
    a = [slot.submit(p, max_new=5) for p in wave]
    slot.run_until_idle()
    b = [paged.submit(p, max_new=5) for p in wave]
    paged.run_until_idle()
    assert [r.tokens for r in a] == [r.tokens for r in b], \
        "paged decode diverged from slot engine"
assert paged.pager.prefix_hits >= 2, paged.pager.stats()
pool = paged.pager.pool
pool.check()
assert pool.n_used + pool.n_free == paged.n_blocks - 1
paged._stamp_kv_watermarks({})
board = watermark_board()
per_block = paged._kv_bytes_static / paged.n_blocks
assert board["kv_cache_bytes"]["current"] == paged._kv_bytes_static
assert board["kv_cache_used_bytes"]["current"] == pool.n_used * per_block
print("paged-serving smoke OK")
PY

echo "== bench_serve_kv smoke (slot-vs-paged capacity harness) =="
# the r20 load harness end to end in --smoke shape: asserts decode
# identity, pool reconciliation, and at least one capacity bar inside
# main() (BENCH_SERVE_KV_r20.json is the committed full-shape run)
JAX_PLATFORMS=cpu python tools/bench_serve_kv.py --smoke > /dev/null
echo "bench_serve_kv smoke OK"

echo "== quantized-serving smoke (r21: weight-only int8 + zero-dispatch tick) =="
# quantize an mnist-scale LM tick in place: census ledger identity must
# be EXACT (predicted params_quantized == measured, byte for byte),
# int8 greedy decode must be token-identical to f32 on the shared
# weights at this vocab, and the steady-state tick must be genuinely
# zero-dispatch: the engine emits `dispatch` spans and the bound tick's
# per-tick Python allocation stays under a pinned budget
JAX_PLATFORMS=cpu python - <<'PY'
import tracemalloc
import numpy as np
import paddle_tpu as pt
from paddle_tpu.core import flags
from paddle_tpu.framework.costs import memory_categories
from paddle_tpu.observability import tracing
from paddle_tpu.observability.memory import state_census
from paddle_tpu.serving import ContinuousBatchingEngine

DIMS = dict(vocab=50, max_len=16, d_model=32, d_inner=64, num_heads=4,
            num_layers=2)
scope = pt.global_scope()
f32 = ContinuousBatchingEngine(n_slots=3, scope=scope, **DIMS)
q8 = ContinuousBatchingEngine(n_slots=3, scope=scope, quant="int8",
                              **DIMS)
assert q8.quant == "int8" and q8.quant_freed_bytes > 0
assert f32.params_bytes_f32 / q8._param_bytes() >= 2.0, \
    (f32.params_bytes_f32, q8._param_bytes())

# ledger identity: predicted category == measured census, exactly
pred = memory_categories(q8._program)
names = [n for n, v in q8._program.current_block().vars.items()
         if v.persistable]
meas = state_census(scope, q8._program, names)["categories"]
assert int(pred["params_quantized"]) == int(meas["params_quantized"]) \
    > 0, (pred, meas)

# decode smoke: int8 tokens == f32 tokens on the shared weights
prompts = [[7], [3, 9], [11, 2, 5]]
a = [f32.submit(p, max_new=5) for p in prompts]
f32.run_until_idle()
flags.set_flag("trace", True)
try:
    mark = tracing.mark()
    b = [q8.submit(p, max_new=5) for p in prompts]
    q8.run_until_idle()
    spans = [s for s in tracing.spans_since(mark)
             if (s.kind, s.name) == ("dispatch", "engine/dispatch")]
finally:
    flags.set_flag("trace", False)
assert [r.tokens for r in a] == [r.tokens for r in b], \
    "int8 greedy decode diverged from f32"
assert spans and q8._m_dispatch.count > 0

# zero-dispatch: the bound tick allocates (almost) nothing per tick
step = q8._step
step.run_bound()
tracemalloc.start()
s0 = tracemalloc.take_snapshot()
for _ in range(50):
    out = step.run_bound()
np.asarray(out[0])
s1 = tracemalloc.take_snapshot()
tracemalloc.stop()
per_tick = sum(max(d.size_diff, 0)
               for d in s1.compare_to(s0, "filename")) / 50
assert per_tick < 2048, f"bound tick allocates {per_tick:.0f} B/tick"
print(f"quantized-serving smoke OK ({per_tick:.0f} B/tick)")
PY

echo "== speculative-decoding smoke (r22: draft propose + one-forward verify) =="
# γ=4 greedy speculation on the paged engine: decode must be
# TOKEN-IDENTICAL to the target-only twin on shared weights (the accept
# rule is structural), the acceptance gauge must be live on the engine
# registry, and the block pool must reconcile with per-round checks on
# (rollbacks included). The full harness is tools/bench_spec.py
# (BENCH_SPEC_r22.json is the committed full-shape run).
JAX_PLATFORMS=cpu PTPU_SPEC_POOL_CHECK=1 python - <<'PY'
import numpy as np
import paddle_tpu as pt
from paddle_tpu.serving import PagedKVEngine, SpecConfig

DIMS = dict(vocab=100, max_len=16, d_model=32, d_inner=64, num_heads=4,
            num_layers=2)
scope = pt.global_scope()
base = PagedKVEngine(n_slots=3, block_size=4, scope=scope, **DIMS)
spec = PagedKVEngine(n_slots=3, block_size=4, scope=scope,
                     speculative=SpecConfig(gamma=4, draft="int8"), **DIMS)
rng = np.random.RandomState(0)
prompts = [rng.randint(1, 100, size=rng.randint(2, 6)).tolist()
           for _ in range(5)]
a = [base.submit(p, max_new=6) for p in prompts]
base.run_until_idle()
b = [spec.submit(p, max_new=6) for p in prompts]
spec.run_until_idle()
assert [r.tokens for r in a] == [r.tokens for r in b], \
    "speculative decode diverged from the target-only twin"
s = spec.spec.stats()
assert s["rounds"] > 0 and 0.0 <= s["acceptance_rate"] <= 1.0
assert spec.target_forwards < base.target_forwards, \
    (spec.target_forwards, base.target_forwards)
text = spec.metrics_registry.expose()
for series in ("ptpu_engine_spec_acceptance_rate",
               "ptpu_engine_spec_tokens_per_target_forward",
               "ptpu_engine_spec_rolled_back_blocks"):
    assert series in text, series
pool = spec.pager.pool
pool.check()
assert pool.n_used + pool.n_free == pool.n_blocks - 1
print(f"speculative smoke OK (acceptance={s['acceptance_rate']:.3f}, "
      f"{spec.tokens_out / spec.target_forwards:.2f} tok/target-fwd "
      f"vs 1.0 plain)")
PY

echo "== bench_spec smoke (speculative amortization harness) =="
# the r22 harness end to end in --smoke shape: asserts greedy identity,
# the ≥1.5x tokens-per-target-forward bar at saturation, per-round pool
# reconciliation, and the params_draft ledger identity inside main()
JAX_PLATFORMS=cpu python tools/bench_spec.py --smoke > /dev/null
echo "bench_spec smoke OK"

echo "== two-tier host-offload smoke (r23: spill + prefetch + exact census) =="
# a paged engine at a deliberately tight device pool with the host tier
# on: decode must be TOKEN-IDENTICAL to an unconstrained-pool twin,
# real spills must have happened, the wire-byte census must reconcile
# EXACTLY (eviction/reload counters x per-block bytes == the transfer
# stream's measured bytes), and the two-pool accounting identity must
# hold. The offload schedule lint must pass on the shipped prefetch
# policy. Full harness: tools/bench_offload.py (BENCH_OFFLOAD_r23.json
# is the committed full-shape run).
JAX_PLATFORMS=cpu python - <<'PY'
import numpy as np
import paddle_tpu as pt
from paddle_tpu.framework import offload as ofl
from paddle_tpu.serving import HostTierConfig, PagedKVEngine

DIMS = dict(vocab=100, max_len=16, d_model=32, d_inner=64, num_heads=4,
            num_layers=2)
scope = pt.global_scope()
rng = np.random.RandomState(0)
prompts = [rng.randint(1, 100, size=rng.randint(3, 9)).tolist()
           for _ in range(8)]
base = PagedKVEngine(n_slots=6, block_size=4, scope=scope, **DIMS)
a = [base.submit(p, max_new=6) for p in prompts]
base.run_until_idle()
two = PagedKVEngine(n_slots=6, block_size=4, n_blocks=9, scope=scope,
                    host_tier=HostTierConfig(host_blocks=32,
                                             prefetch_distance=2,
                                             rotate_quantum=4), **DIMS)
b = [two.submit(p, max_new=6) for p in prompts]
two.run_until_idle()
assert [r.tokens for r in a] == [r.tokens for r in b], \
    "two-tier decode diverged from the unconstrained twin"
assert two.pager.host_evictions > 0, "no spill pressure — smoke is dead"
per = two._ht_per_block_bytes
assert two.ht_d2h_bytes == two.pager.host_evictions * per, \
    (two.ht_d2h_bytes, two.pager.host_evictions, per)
assert two.ht_h2d_bytes == two.pager.host_reloads * per, \
    (two.ht_h2d_bytes, two.pager.host_reloads, per)
two.pager.check_two_tier()
events = ofl.kv_prefetch_events({"r%d" % t: t for t in range(2, 6)}, 2)
assert ofl.check_schedule(events) == [], "shipped prefetch policy lints dirty"
print(f"offload smoke OK ({two.pager.host_evictions} spills, "
      f"{two.ht_d2h_bytes} B d2h == census, hit_rate="
      f"{two.pager.stats()['host_tier']['prefetch_hit_rate']:.2f})")
PY

echo "== lint_program --offload (named diagnostic: offload-use-before-arrival) =="
JAX_PLATFORMS=cpu python tools/lint_program.py --model mnist --offload > /dev/null
JAX_PLATFORMS=cpu python tools/lint_program.py \
    --model transformer_lm_paged_decode_tick --offload > /dev/null
echo "lint --offload OK"

echo "== bench_offload smoke (two-tier capacity harness) =="
# the r23 harness end to end in --smoke shape: asserts token identity,
# the exact per-cell wire census, the ≥1.5x admitted-concurrency bar at
# the anchor pool, optimizer-offload loss identity, and the planner's
# refuse/accept verdicts on the stash roofline inside main()
JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python tools/bench_offload.py --smoke > /dev/null
echo "bench_offload smoke OK"

echo "== serving ownership verifier (r24: model check + seeded mutation + lint contract) =="
# the block-lifetime model checker must exhaustively clear the shipped
# pager protocol at its default scope (the state count is the proof of
# coverage), and a seeded protocol mutation must be caught BY NAME —
# both halves of the static_analysis.md §5 contract
JAX_PLATFORMS=cpu python - <<'PY'
from paddle_tpu.framework.ownership import ModelChecker, MUTATIONS

res = ModelChecker().run()
assert res.ok, res.violations
assert res.states_explored == 233 and res.transitions == 676, \
    (res.states_explored, res.transitions)
mut = ModelChecker(mutation="leaked-release").run()
assert not mut.ok and MUTATIONS["leaked-release"] in mut.codes(), \
    mut.codes()
print(f"ownership model check OK ({res.states_explored} states / "
      f"{res.transitions} transitions clean; seeded leaked-release "
      f"caught as {MUTATIONS['leaked-release']})")
PY

# lint --serving: clean on the shipped paged tick builder (exit 0, the
# report's serving section populated) and the --json exit-code contract
JAX_PLATFORMS=cpu python tools/lint_program.py \
    --model transformer_lm_paged_decode_tick --serving > /dev/null
JAX_PLATFORMS=cpu python tools/lint_program.py \
    --model transformer_lm_paged_decode_tick --serving --json \
    | python -c '
import json, sys
reports = json.load(sys.stdin)
sv = reports[0]["serving"]
mc = sv["model_check"]
assert mc["violations"] == 0 and mc["states_explored"] == 233, mc
assert sv["violations"] == 0, sv["violations"]
print("lint --serving OK (json contract, model check "
      "%d states)" % mc["states_explored"])
'

echo "CI OK"
