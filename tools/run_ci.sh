#!/bin/bash
# CI driver (≙ reference paddle/scripts/paddle_build.sh: build + test +
# API check + benchmark smoke). Runs on the virtual 8-device CPU mesh.
set -e
cd "$(dirname "$0")/.."

echo "== build native runtime =="
sh paddle_tpu/native/build.sh

echo "== API surface check =="
JAX_PLATFORMS=cpu python tools/print_signatures.py | sort > /tmp/api_current.txt
sort API.spec > /tmp/api_golden.txt
diff /tmp/api_golden.txt /tmp/api_current.txt || {
    echo "API surface drifted — review and run tools/print_signatures.py --update"; exit 1; }

echo "== test pyramid (~15 min on 2 cores) =="
XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -x

echo "== benchmark smoke =="
JAX_PLATFORMS=cpu python tools/benchmark.py --model mnist --batch_size 8 \
    --iters 3 --warmup 1

echo "CI OK"
