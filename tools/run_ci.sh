#!/bin/bash
# CI driver (≙ reference paddle/scripts/paddle_build.sh: build + test +
# API check + benchmark smoke). Runs on the virtual 8-device CPU mesh.
#
#   tools/run_ci.sh          full tier (suite measured at ~40 min on this
#                            2-core box single-process — budget an hour)
#   tools/run_ci.sh quick    smoke tier (~5 min): build + API check +
#                            `-m quick`-marked tests + bench smoke
set -e
cd "$(dirname "$0")/.."
TIER="${1:-full}"

echo "== build native runtime =="
PTPU_BUILD_PREDICT=1 sh paddle_tpu/native/build.sh || \
    sh paddle_tpu/native/build.sh   # predictor needs TF libs; lib alone if absent

echo "== API surface check =="
JAX_PLATFORMS=cpu python tools/print_signatures.py | sort > /tmp/api_current.txt
sort API.spec > /tmp/api_golden.txt
diff /tmp/api_golden.txt /tmp/api_current.txt || {
    echo "API surface drifted — review and run tools/print_signatures.py --update"; exit 1; }

if [ "$TIER" = "quick" ]; then
    echo "== quick test tier (~5 min) =="
    # the fusion numeric-parity tests (tests/test_fusion.py) ride this
    # tier via their `quick` marks — the fuse passes are default-on, so
    # every smoke must see them verified
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python -m pytest tests/ -q -x -m quick
else
    echo "== full test pyramid (~29 min on 2 cores with -n 2; measured) =="
    # tier-1 selection: everything but the slow-marked A/B bench smokes
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python -m pytest tests/ -q -n 2 --dist load -m 'not slow'
fi

echo "== benchmark smoke =="
JAX_PLATFORMS=cpu python tools/benchmark.py --model mnist --batch_size 8 \
    --iters 3 --warmup 1

echo "== serving-engine smoke =="
# continuous-batching engine end to end: submit through the RPC server,
# decode over the slot cache, check a mid-batch join completes (fast:
# tiny LM, ~15 s including compile)
JAX_PLATFORMS=cpu python - <<'PY'
from paddle_tpu.serving_engine import (ContinuousBatchingEngine,
                                       EngineClient, EngineServer)
eng = ContinuousBatchingEngine(n_slots=2, vocab=100, max_len=16,
                               d_model=32, d_inner=64, num_heads=4,
                               num_layers=2)
with EngineServer(eng) as srv:
    host, port = srv.address
    with EngineClient(host, port) as c:
        long_tag = c.send_gen([3], max_new=8)
        short_tag = c.send_gen([5], max_new=2)      # joins mid-batch
        done = dict((t, toks) for t, toks, _ in
                    (c.recv_done(), c.recv_done()))
        assert len(done[long_tag]) == 8 and len(done[short_tag]) == 2
print("serving-engine smoke OK")
PY

echo "CI OK"
