#!/usr/bin/env python
"""Bubble census for the pipeline-parallel executor (r09).

Decomposes each pipeline step into compute / bubble / boundary-comm per
stage and pins the measured bubble fraction against the analytic
(K-1)/(M+K-1) model:

- STRUCTURAL: the per-stage idle-slot census comes from the SAME tick
  tables the device executes (parallel/pipeline.py build_schedule), so the
  bubble fraction is an exact property of the compiled schedule, not an
  estimate — for both GPipe and 1F1B it is exactly (K-1)/(M+K-1).
- MEASURED: wall-clock step time across M must follow the slot model
  t(M) = slot_ms * 2(M+K-1) + overhead; the probe fits slot_ms/overhead
  by least squares and reports the fit R² plus the implied bubble time
  bubble_ms = 2(K-1) * slot_ms per step. (On this CPU mesh the boundary
  ppermute rides inside the slot — its bytes are reported analytically
  via pp_boundary_wire_bytes, the same ring accounting as the r08 comm
  census.)
- HLO: the compiled step must contain exactly ONE boundary-activation and
  ONE boundary-gradient collective-permute (one send/recv pair per
  boundary direction per tick), independent of M — asserted here and in
  tests/test_pipeline_parallel.py.

Usage:
    python tools/probe_bubble.py --stages 4 --microbatches 4,8,16 \
        --out PROBE_BUBBLE_r09.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))


def _build_mlp(depth, width):
    import paddle_tpu as pt
    from paddle_tpu import layers
    x = layers.data("x", shape=[width])
    label = layers.data("label", shape=[1], dtype="int64")
    h = x
    for _ in range(depth):
        h = layers.fc(h, size=width, act="relu")
    loss = layers.mean(layers.softmax_with_cross_entropy(
        layers.fc(h, size=10), label))
    pt.optimizer.MomentumOptimizer(0.1, momentum=0.9).minimize(loss)
    return loss


def _time_step(exe, feed, loss, iters, windows=5):
    """(best_ms, [per-window mean ms]) — best-of-windows with the spread
    committed (this 2-core CPU box is noisy; r08 discipline)."""
    import numpy as np
    exe.run(feed=feed, fetch_list=[loss])          # compile + warm
    means = []
    for _ in range(windows):
        t0 = time.time()
        out = None
        for _ in range(iters):
            out = exe.run(feed=feed, fetch_list=[loss],
                          return_numpy=False)
        float(np.asarray(out[0]).ravel()[0])
        means.append((time.time() - t0) / iters * 1e3)
    return min(means), means


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--stages", type=int, default=4)
    p.add_argument("--microbatches", default="4,8,16")
    p.add_argument("--schedules", default="gpipe,1f1b")
    p.add_argument("--depth", type=int, default=8)
    p.add_argument("--width", type=int, default=128)
    p.add_argument("--batch_per_microbatch", type=int, default=4)
    p.add_argument("--iters", type=int, default=5)
    p.add_argument("--out", default=None)
    args = p.parse_args()

    import numpy as np
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.parallel import ParallelExecutor
    from paddle_tpu.parallel.mesh import DeviceMesh
    from paddle_tpu.parallel.pipeline import (pp_boundary_wire_bytes,
                                              schedule_census)
    from paddle_tpu.parallel.strategy import BuildStrategy
    from probe_common import collective_census

    K = args.stages
    ms = [int(x) for x in args.microbatches.split(",")]
    result = {"probe": "pipeline_bubble", "num_stages": K,
              "model": f"mlp depth={args.depth} width={args.width}",
              "device": jax.devices()[0].platform,
              "iters": args.iters, "schedules": {}}
    for sched in args.schedules.split(","):
        rows = []
        for m in ms:
            pt.reset_default_programs()
            pt.reset_global_scope()
            with pt.core.unique_name.guard():
                loss = _build_mlp(args.depth, args.width)
            bst = BuildStrategy(pipeline_stages=K, num_microbatches=m,
                                pipeline_schedule=sched)
            mesh = DeviceMesh(jax.devices()[:K], {"pp": K})
            exe = ParallelExecutor(loss_name=loss.name, mesh=mesh,
                                   build_strategy=bst)
            pt.Executor().run(pt.default_startup_program())
            bs = m * args.batch_per_microbatch
            rng = np.random.RandomState(0)
            feed = {"x": rng.rand(bs, args.width).astype("f4"),
                    "label": rng.randint(0, 10, (bs, 1)).astype("i8")}
            step_ms, window_ms = _time_step(exe, feed, loss, args.iters)
            census = schedule_census(sched, m, K)
            prog = exe._prepare_program(pt.default_main_program(),
                                        pt.global_scope())
            wire = pp_boundary_wire_bytes(prog,
                                          args.batch_per_microbatch)
            cs = list(exe._cache.values())[-1]
            scope = pt.global_scope()
            hlo = cs.fn.lower(
                tuple(jnp.asarray(feed[n]) for n in cs.feed_names),
                tuple(scope.get(n) for n in cs.ro_names),
                tuple(scope.get(n) for n in cs.rw_names),
                np.uint32(0)).compile().as_text()
            hlo_census = collective_census(hlo)
            n_perm = len(hlo_census.get("collective-permute", []))
            assert n_perm == 2, (
                f"expected exactly 2 collective-permutes (one boundary "
                f"act + one boundary grad shift per tick), got {n_perm}")
            rows.append({
                "num_microbatches": m,
                "ticks": census["ticks"],
                "step_ms": round(step_ms, 3),
                "window_ms": [round(w, 3) for w in window_ms],
                "bubble_fraction_census": census["bubble_fraction"],
                "bubble_fraction_analytic":
                    census["analytic_bubble_fraction"],
                "idle_slots_per_stage": census["idle_slots_per_stage"],
                "peak_stash_per_stage": census["peak_stash_per_stage"],
                "act_stash_depth": census["act_stash_depth"],
                "pp_boundary_bytes_per_step": wire["pp_boundary_bytes"],
                "boundary_buffer_numel": wire["buffer_numel"],
                "hlo_collective_permutes": n_perm,
            })
        # least-squares fit: step_ms = slot_ms * ticks + overhead_ms
        t = np.asarray([r["ticks"] for r in rows], float)
        y = np.asarray([r["step_ms"] for r in rows], float)
        a = np.vstack([t, np.ones_like(t)]).T
        (slot_ms, overhead_ms), _, rank, _ = np.linalg.lstsq(a, y,
                                                             rcond=None)
        # R^2 from the residuals of the returned solution, not lstsq's
        # `res` (empty when the system is rank-deficient or has <= 2
        # points, which would masquerade as a perfect fit); an
        # underdetermined fit reports r2 = None and trips the caveat.
        ss_tot = float(((y - y.mean()) ** 2).sum())
        if len(t) > 2 and rank == 2 and ss_tot > 0:
            ss_res = float(((y - a @ np.array([slot_ms, overhead_ms]))
                            ** 2).sum())
            r2 = 1.0 - ss_res / ss_tot
        else:
            r2 = None
        for r in rows:
            busy = r["step_ms"] - overhead_ms
            r["bubble_ms_implied"] = round(2 * (K - 1) * float(slot_ms), 3)
            r["bubble_fraction_measured"] = (
                round(2 * (K - 1) * float(slot_ms) / busy, 4)
                if busy > 0 else None)
        entry = {
            "rows": rows,
            "slot_ms_fit": round(float(slot_ms), 4),
            "overhead_ms_fit": round(float(overhead_ms), 4),
            "fit_r2": round(r2, 4) if r2 is not None else None,
            "note": "bubble_fraction_census is exact (read from the "
                    "executed tick tables); bubble_fraction_measured = "
                    "2(K-1)*slot_ms / (step_ms - overhead_ms) from the "
                    "wall-clock fit",
        }
        if r2 is None or r2 < 0.9:
            entry["fit_caveat"] = (
                "wall-clock fit degraded by CPU-mesh scheduling noise "
                "(see window_ms spreads) — the slot model is advisory "
                "here; the census fields are the exact claim and the "
                "TPU-transferable one")
        result["schedules"][sched] = entry
    text = json.dumps(result, indent=1)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    print(text)


if __name__ == "__main__":
    main()
