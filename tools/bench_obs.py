"""BENCH_OBS: the r12 predicted-vs-measured cost-ledger artifact.

Runs mnist (mlp) and transformer_lm through the manual parallel modes on
the virtual 8-device CPU mesh — dp2 ReduceScatter and dp2 x pp2 (1F1B)
— and commits one CostLedger joining:

  predicted  framework.costs.predict() over the REWRITTEN program
  measured   the compiled step's HLO collective census (exact bytes),
             span aggregates from the observability tracer, step wall
             time
  checks     predicted wire bytes == census EXACTLY (r08/r11 balance),
             pipeline boundary structure (exactly 2 permutes at the
             predicted buffer size, r09), bubble fraction vs the
             schedule tables within the r09 2% band, and the tracing
             overhead budget (<= 3% of step time on, <= 0.5% off).

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python tools/bench_obs.py --out BENCH_OBS_r12.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _build_mnist_mlp(rng, batch):
    import paddle_tpu as pt
    from paddle_tpu import layers
    x = layers.data("x", shape=[64])
    label = layers.data("label", shape=[1], dtype="int64")
    h = layers.fc(x, size=128, act="relu")
    h2 = layers.fc(h, size=64, act="relu")
    loss = layers.mean(layers.softmax_with_cross_entropy(
        layers.fc(h2, size=10), label))
    pt.optimizer.MomentumOptimizer(0.1, momentum=0.9).minimize(loss)
    feed = {"x": rng.rand(batch, 64).astype("float32"),
            "label": rng.randint(0, 10, (batch, 1)).astype("int64")}
    return loss, feed


def _build_transformer_lm(rng, batch):
    import paddle_tpu as pt
    from paddle_tpu.models import transformer
    T = 8
    loss, _ = transformer.transformer_lm(
        vocab=64, max_len=T, d_model=32, d_inner=64, num_heads=4,
        num_layers=2, dropout=0.0, mean_loss=True)
    pt.optimizer.AdamOptimizer(1e-3).minimize(loss)
    feed = {"tokens": rng.randint(0, 64, (batch, T)).astype("int64"),
            "tokens@SEQLEN": np.full((batch,), T, "int32"),
            "targets": rng.randint(0, 64, (batch, T)).astype("int64")}
    return loss, feed


BUILDERS = {"mnist": _build_mnist_mlp, "transformer_lm":
            _build_transformer_lm}


def _compiled_hlo(exe, feed):
    import jax.numpy as jnp
    import paddle_tpu as pt
    cs = list(exe._cache.values())[-1]
    scope = pt.global_scope()
    feed_vals = tuple(jnp.asarray(feed[n]) if n in feed else scope.get(n)
                      for n in cs.feed_names)
    ro = tuple(scope.get(n) for n in cs.ro_names)
    rw = tuple(scope.get(n) for n in cs.rw_names)
    return cs.fn.lower(feed_vals, ro, rw,
                       np.uint32(0)).compile().as_text()


def run_config(led, model, mode, batch, iters):
    """One ledger row: model x parallel config, predicted + measured +
    checks."""
    import jax
    import paddle_tpu as pt
    from paddle_tpu.framework.costs import collective_census
    from paddle_tpu.observability import tracing
    from paddle_tpu.parallel import ParallelExecutor
    from paddle_tpu.parallel.mesh import DeviceMesh
    from paddle_tpu.parallel.strategy import BuildStrategy, ReduceStrategy

    rng = np.random.RandomState(7)
    pt.reset_default_programs()
    pt.reset_global_scope()
    with pt.core.unique_name.guard():
        loss, feed = BUILDERS[model](rng, batch)

    bst = BuildStrategy()
    bst.reduce_strategy = ReduceStrategy.ReduceScatter
    if mode == "dp2":
        mesh = DeviceMesh(jax.devices()[:2], {"dp": 2})
        pp = 0
    elif mode == "dp2xpp2":
        bst.pipeline_stages = 2
        bst.num_microbatches = 4
        bst.pipeline_schedule = "1f1b"
        mesh = DeviceMesh(jax.devices()[:4], {"dp": 2, "pp": 2})
        pp = 2
    else:
        raise ValueError(mode)
    pexe = ParallelExecutor(loss_name=loss.name, build_strategy=bst,
                            mesh=mesh)
    pt.Executor().run(pt.default_startup_program())
    pexe.run(feed=feed, fetch_list=[loss])       # compile + first step

    mark = tracing.mark()
    t0 = time.time()
    for _ in range(iters):
        out = pexe.run(feed=feed, fetch_list=[loss], return_numpy=False)
    jax.block_until_ready(out)
    step_ms = (time.time() - t0) / iters * 1e3
    window = tracing.spans_since(mark)

    report = pexe.cost_report(nominal_batch=batch)
    census = collective_census(_compiled_hlo(pexe, feed))

    row = led.row(f"{model}_{mode}", model=model, mode=mode,
                  batch_size=batch, reduce_mode="reduce_scatter",
                  devices=pexe.device_count)
    row.set_prediction(report)
    row.set_census(census, 2, min_bytes=8)       # dp degree = 2
    row.set_spans(tracing.aggregate(window))
    row.set_measured(step_ms=round(step_ms, 3), iters=iters,
                     spans_per_step=len(window) / iters)
    chk = row.check_wire_bytes_exact()
    print(json.dumps({"row": row.name, "check": chk}), flush=True)
    assert chk["ok"], chk
    if pp:
        b = row.check_pp_boundary()
        print(json.dumps({"row": row.name, "check": b}), flush=True)
        assert b["ok"], b
        pipe = report["pipeline"]
        bub = row.check_bubble_fraction(pipe["analytic_bubble_fraction"],
                                        band=0.02)
        print(json.dumps({"row": row.name, "check": bub}), flush=True)
        assert bub["ok"], bub
    return step_ms, len(window) / iters


def overhead_census(led, step_ms, spans_per_step):
    """Tracing overhead budget: measured per-span enter/exit cost x spans
    per step vs the measured step time, both flag states."""
    from paddle_tpu.core import flags
    from paddle_tpu.observability import tracing

    on_cost = tracing.span_overhead_s()
    flags.set_flag("trace", False)
    try:
        off_cost = tracing.span_overhead_s()
    finally:
        flags.set_flag("trace", True)
    frac_on = on_cost * spans_per_step / (step_ms / 1e3)
    frac_off = off_cost * spans_per_step / (step_ms / 1e3)
    row = led.row("tracing_overhead", step_ms=round(step_ms, 3),
                  spans_per_step=spans_per_step)
    row.set_measured(
        per_span_us_enabled=round(on_cost * 1e6, 3),
        per_span_us_disabled=round(off_cost * 1e6, 3),
        overhead_fraction_enabled=round(frac_on, 6),
        overhead_fraction_disabled=round(frac_off, 6))
    c1 = row._check("overhead_enabled", round(frac_on, 6), 0.03,
                    "<= 3% of step", frac_on <= 0.03)
    c2 = row._check("overhead_disabled", round(frac_off, 6), 0.005,
                    "<= 0.5% of step", frac_off <= 0.005)
    print(json.dumps({"row": "tracing_overhead", "checks": [c1, c2]}),
          flush=True)
    assert c1["ok"] and c2["ok"], (c1, c2)


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out", default=os.path.join(REPO,
                                                 "BENCH_OBS_r12.json"))
    p.add_argument("--iters", type=int, default=10)
    args = p.parse_args()

    import jax
    from paddle_tpu.observability.ledger import CostLedger

    led = CostLedger("r12", meta={
        "mesh": "virtual CPU x8 (byte/structure checks are exact "
                "properties of the compiled HLO and transfer to TPU "
                "unchanged; ms numbers are CPU-mesh)",
        "devices": [str(d) for d in jax.devices()[:2]],
    })
    worst = (0.0, 0.0)
    for model in ("mnist", "transformer_lm"):
        for mode in ("dp2", "dp2xpp2"):
            step_ms, sps = run_config(led, model, mode,
                                      batch=16, iters=args.iters)
            if model == "mnist" and mode == "dp2":
                # budget vs the FASTEST benched step: the binding case
                worst = (step_ms, sps)
    overhead_census(led, *worst)
    path = led.write(args.out)
    print(json.dumps({"artifact": path, "ok": led.ok}), flush=True)
    assert led.ok


if __name__ == "__main__":
    main()
