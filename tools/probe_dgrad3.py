"""probe_dgrad, final methodology: repetitions run INSIDE one jit via a
ROLLED lax.scan, so per-dispatch tunnel overhead (which dominated
probe_dgrad2's 5-15 ms kernels at ~200 GB/s apparent bandwidth) is
amortized over 32 on-device executions per call. A rolled loop body
executes every iteration (no cross-iteration CSE), and folding the carry
into the first operand (+ carry*0, unfoldable for floats) blocks
loop-invariant hoisting. Host-value realization is the barrier.

    env PYTHONPATH=/root/.axon_site:/root/repo python tools/probe_dgrad3.py
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

DN = ("NHWC", "HWIO", "NHWC")
REPS = 32          # scan length inside one dispatch


def _scan_bench(op, args):
    """op(*args) -> array. Builds jit(f) running REPS executions inside a
    ROLLED lax.scan in ONE dispatch. Identical overhead lands on both
    sides of every A/B."""

    @jax.jit
    def f():
        def body(carry, _):
            a0 = args[0] + carry.astype(args[0].dtype) * 0
            out = op(a0, *args[1:])
            return carry + out.reshape(-1)[0].astype(jnp.float32), None
        carry, _ = jax.lax.scan(body, jnp.float32(0), None, length=REPS)
        return carry
    return f, ()


def _time_scan(f, args, windows=5):
    float(np.asarray(f(*args)))                     # compile + drain
    best = None
    for _ in range(windows):
        t0 = time.time()
        out = f(*args)
        float(np.asarray(out))                      # trusted barrier
        dt = (time.time() - t0) / REPS
        best = dt if best is None else min(best, dt)
    return best


def _cost_single(op, args1):
    ex = jax.jit(op).lower(*args1).compile()
    ca = ex.cost_analysis()
    ca = ca[0] if isinstance(ca, (list, tuple)) else (ca or {})
    return (float(ca.get("bytes accessed", 0.0)),
            float(ca.get("flops", 0.0)))


def _report(name, op, args1):
    f, fargs = _scan_bench(op, args1)
    t = _time_scan(f, fargs)
    b, fl = _cost_single(op, args1)
    row = {"variant": name, "ms": round(t * 1e3, 3),
           "bytes_MB": round(b / 1e6, 1), "flops_G": round(fl / 1e9, 2),
           "achieved_GBps": round(b / t / 1e9, 1) if b else None,
           "achieved_TFLOPs": round(fl / t / 1e12, 2) if fl else None,
           "reps_per_dispatch": REPS}
    print(json.dumps(row), flush=True)
    return row


def conv_fwd(x, w, stride=1):
    return jax.lax.conv_general_dilated(
        x, w, window_strides=(stride, stride), padding="SAME",
        dimension_numbers=DN)


def main():
    rng = np.random.RandomState(0)
    results = {}

    B, HW, Ci, Co = 256, 56, 256, 64

    def mk(shape):
        return jnp.asarray(rng.rand(*shape).astype("float32"),
                           jnp.bfloat16)

    dys = mk((B, HW, HW, Co))
    ws = mk((1, 1, Ci, Co))
    xs = mk((B, HW, HW, Ci))

    def dgrad_conv_1x1(dy, w):
        _, vjp = jax.vjp(
            lambda x_: conv_fwd(x_, w),
            jnp.zeros((B, HW, HW, Ci), dy.dtype))
        return vjp(dy)[0]

    def dgrad_dot_1x1(dy, w):
        dy2 = dy.reshape(-1, Co)
        w2 = w.reshape(Ci, Co)
        dx = jax.lax.dot_general(dy2, w2, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        return dx.astype(dy.dtype).reshape(B, HW, HW, Ci)

    print("== A: 1x1 dgrad [256,56,56,64] -> [256,56,56,256]", flush=True)
    a_conv = _report("dgrad_1x1_conv_emitter", dgrad_conv_1x1, (dys, ws))
    a_dot = _report("dgrad_1x1_dot_general", dgrad_dot_1x1, (dys, ws))
    results["dgrad_1x1_speedup_dot_over_conv"] = round(
        a_conv["ms"] / a_dot["ms"], 3)

    def vjp_conv_1x1(x, w, dy):
        y, vjp = jax.vjp(lambda x_, w_: conv_fwd(x_, w_), x, w)
        dx, dw = vjp(dy)
        return dx + y.sum() * 0 + dw.sum() * 0

    def vjp_dot_1x1(x, w, dy):
        x2 = x.reshape(-1, Ci)
        w2 = w.reshape(Ci, Co)
        dy2 = dy.reshape(-1, Co)

        def f(x2_, w2_):
            return jax.lax.dot_general(
                x2_, w2_, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32).astype(x2_.dtype)
        y2, vjp = jax.vjp(f, x2, w2)
        dx2, dw2 = vjp(dy2)
        return (dx2.reshape(B, HW, HW, Ci) + y2.sum() * 0 + dw2.sum() * 0)

    print("== A': 1x1 fwd+bwd vjp", flush=True)
    av_conv = _report("vjp_1x1_conv_emitter", vjp_conv_1x1,
                      (xs, ws, dys))
    av_dot = _report("vjp_1x1_dot_general", vjp_dot_1x1, (xs, ws, dys))
    results["vjp_1x1_speedup_dot_over_conv"] = round(
        av_conv["ms"] / av_dot["ms"], 3)

    # ---- B: 3x3 dgrad at 56x56, 64->64 ----------------------------------
    C3 = 64
    xs3 = mk((B, HW, HW, C3))
    ws3 = mk((3, 3, C3, C3))
    dys3 = mk((B, HW, HW, C3))

    def dgrad_conv_3x3(dy, w):
        _, vjp = jax.vjp(
            lambda x_: conv_fwd(x_, w),
            jnp.zeros((B, HW, HW, C3), dy.dtype))
        return vjp(dy)[0]

    def dgrad_im2col_3x3(dy, w):
        patches = jax.lax.conv_general_dilated_patches(
            dy, (3, 3), (1, 1), "SAME", dimension_numbers=DN)
        wf = jnp.flip(w, (0, 1))
        wr = jnp.transpose(wf, (3, 0, 1, 2)).reshape(9 * C3, C3)
        dx = jax.lax.dot_general(
            patches.reshape(-1, 9 * C3), wr, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dx.astype(dy.dtype).reshape(B, HW, HW, C3)

    print("== B: 3x3 dgrad 64ch @56x56", flush=True)
    b_conv = _report("dgrad_3x3_conv_emitter", dgrad_conv_3x3,
                     (dys3, ws3))
    b_im2col = _report("dgrad_3x3_im2col_dot", dgrad_im2col_3x3,
                       (dys3, ws3))
    results["dgrad_3x3_speedup_im2col_over_conv"] = round(
        b_conv["ms"] / b_im2col["ms"], 3)

    # ---- C: full 3x3 vjp ------------------------------------------------
    def vjp_conv_3x3(x, w, dy):
        y, vjp = jax.vjp(lambda x_, w_: conv_fwd(x_, w_), x, w)
        dx, dw = vjp(dy)
        return dx + y.sum() * 0 + dw.sum() * 0

    print("== C: 3x3 fwd+bwd vjp (reference point)", flush=True)
    _report("vjp_3x3_conv_emitter", vjp_conv_3x3, (xs3, ws3, dys3))

    print(json.dumps({"exp": "dgrad_probe3_summary", **results}),
          flush=True)


if __name__ == "__main__":
    main()
