"""Conv-backward emitter probes, consolidated (r12).

One flag-driven driver replacing the four numbered copies
(probe_dgrad{,2,3,4}.py), which were successive METHODOLOGY refinements
of one question (VERDICT r4 #1: is the conv dgrad's HBM excess
program-reducible?). The timing modes preserve that lineage:

  --timing simple        one arg-tuple, best-of-windows (the original
                         probe_dgrad; KNOWN to overstate identical-call
                         throughput — kept for methodology A/Bs)
  --timing interleaved   4 distinct input variants cycled per iteration
                         (probe_dgrad2's fix for the CSE artifact)
  --timing scan          32 reps inside one jit via a rolled lax.scan —
                         per-dispatch tunnel overhead amortized
                         (probe_dgrad3's final form)

Experiments (--exp, repeatable):
  dgrad_1x1     isolated 1x1 dgrad: conv emitter vs one dot_general
  vjp_1x1       full fwd+bwd vjp of the 1x1 conv: all-conv vs all-dot
  dgrad_3x3     3x3 dgrad: conv emitter vs im2col+dot
  mixed_1x1     custom_vjp with conv fwd + dot dgrad + conv wgrad — each
                half on its winning emitter (probe_dgrad4's decider; the
                PTPU_CONV1X1_MIXED_VJP flag ships this lowering)

    python tools/probe_dgrad.py --exp dgrad_1x1 --timing scan
    python tools/probe_dgrad.py --exp all --timing interleaved
"""

from __future__ import annotations

import argparse
import json
import time

import jax
import jax.numpy as jnp
import numpy as np

DN = ("NHWC", "HWIO", "NHWC")
NVAR = 4           # distinct input variants (interleaved mode)
REPS = 32          # scan length inside one dispatch (scan mode)
B, HW, Ci, Co = 256, 56, 256, 64
C3 = 64

EXPERIMENTS = ("dgrad_1x1", "vjp_1x1", "dgrad_3x3", "mixed_1x1")


def _sync(out):
    """Host-value realization is the ONLY trusted barrier through the
    axon tunnel: fetch one scalar element of the final output — 4 bytes
    over the link, ordered after the whole queue."""
    x = out
    while isinstance(x, (tuple, list)):
        x = x[0]
    return float(np.asarray(x[(0,) * x.ndim] if x.ndim else x))


def _time_simple(fn, variants, iters, windows):
    _sync(fn(*variants[0]))
    best = None
    for _ in range(windows):
        t0 = time.time()
        out = None
        for _ in range(iters):
            out = fn(*variants[0])
        _sync(out)
        dt = (time.time() - t0) / iters
        best = dt if best is None else min(best, dt)
    return best


def _time_interleaved(fn, variants, iters, windows):
    for v in variants:
        _sync(fn(*v))
    best = None
    for _ in range(windows):
        t0 = time.time()
        out = None
        for i in range(iters):
            out = fn(*variants[i % len(variants)])
        _sync(out)
        dt = (time.time() - t0) / iters
        best = dt if best is None else min(best, dt)
    return best


def _time_scan(op, variants, iters, windows):
    """REPS executions inside ONE jit dispatch via a rolled lax.scan; the
    carry folds into the first operand (+ carry*0, unfoldable for floats)
    so nothing hoists or CSEs."""
    args = variants[0]

    @jax.jit
    def f():
        def body(carry, _):
            a0 = args[0] + carry.astype(args[0].dtype) * 0
            out = op(a0, *args[1:])
            while isinstance(out, (tuple, list)):
                out = out[0]
            return carry + out.reshape(-1)[0].astype(jnp.float32), None
        carry, _ = jax.lax.scan(body, jnp.float32(0), None, length=REPS)
        return carry

    float(np.asarray(f()))
    best = None
    for _ in range(windows):
        t0 = time.time()
        float(np.asarray(f()))
        dt = (time.time() - t0) / REPS
        best = dt if best is None else min(best, dt)
    return best


TIMING = {"simple": _time_simple, "interleaved": _time_interleaved,
          "scan": _time_scan}


def _cost(fn, args):
    ex = jax.jit(fn).lower(*args).compile()
    ca = ex.cost_analysis()
    ca = ca[0] if isinstance(ca, (list, tuple)) else (ca or {})
    return (float(ca.get("bytes accessed", 0.0)),
            float(ca.get("flops", 0.0)))


def _report(name, fn, variants, args):
    timer = TIMING[args.timing]
    jfn = fn if args.timing == "scan" else jax.jit(fn)
    t = timer(jfn, variants, args.iters, args.windows)
    b, f = _cost(fn, variants[0])
    row = {"variant": name, "timing": args.timing,
           "ms": round(t * 1e3, 3),
           "bytes_MB": round(b / 1e6, 1), "flops_G": round(f / 1e9, 2),
           "achieved_GBps": round(b / t / 1e9, 1) if b else None,
           "achieved_TFLOPs": round(f / t / 1e12, 2) if f else None,
           "n_distinct_inputs": (len(variants)
                                 if args.timing == "interleaved" else 1)}
    print(json.dumps(row), flush=True)
    return row


def conv_fwd(x, w, stride=1):
    return jax.lax.conv_general_dilated(
        x, w, window_strides=(stride, stride), padding="SAME",
        dimension_numbers=DN)


def _mk(rng, shape):
    return [jnp.asarray(rng.rand(*shape).astype("float32"), jnp.bfloat16)
            for _ in range(NVAR)]


def exp_dgrad_1x1(args, rng, results):
    dys, ws, xs = (_mk(rng, (B, HW, HW, Co)), _mk(rng, (1, 1, Ci, Co)),
                   _mk(rng, (B, HW, HW, Ci)))

    def dgrad_conv(dy, w, x):
        _, vjp = jax.vjp(lambda x_: conv_fwd(x_, w), x)
        return vjp(dy)[0]

    def dgrad_dot(dy, w, x):
        dy2 = dy.reshape(-1, Co)
        dx = jax.lax.dot_general(dy2, w.reshape(Ci, Co),
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        return dx.astype(dy.dtype).reshape(B, HW, HW, Ci)

    print("== dgrad_1x1 [256,56,56,64] -> [256,56,56,256]", flush=True)
    var3 = list(zip(dys, ws, xs))
    a = _report("dgrad_1x1_conv_emitter", dgrad_conv, var3, args)
    b = _report("dgrad_1x1_dot_general", dgrad_dot, var3, args)
    np.testing.assert_allclose(
        np.asarray(dgrad_conv(*var3[0]), np.float32),
        np.asarray(dgrad_dot(*var3[0]), np.float32), rtol=2e-2, atol=1e-2)
    results["dgrad_1x1_speedup_dot_over_conv"] = round(a["ms"] / b["ms"], 3)


def exp_vjp_1x1(args, rng, results):
    xs, ws, dys = (_mk(rng, (B, HW, HW, Ci)), _mk(rng, (1, 1, Ci, Co)),
                   _mk(rng, (B, HW, HW, Co)))

    def vjp_conv(x, w, dy):
        y, vjp = jax.vjp(lambda x_, w_: conv_fwd(x_, w_), x, w)
        return (y,) + vjp(dy)

    def vjp_dot(x, w, dy):
        x2, w2, dy2 = x.reshape(-1, Ci), w.reshape(Ci, Co), dy.reshape(-1,
                                                                       Co)

        def f(x2_, w2_):
            return jax.lax.dot_general(
                x2_, w2_, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32).astype(x2_.dtype)
        y2, vjp = jax.vjp(f, x2, w2)
        dx2, dw2 = vjp(dy2)
        return (y2.reshape(B, HW, HW, Co), dx2.reshape(B, HW, HW, Ci),
                dw2.reshape(1, 1, Ci, Co))

    print("== vjp_1x1 fwd+bwd", flush=True)
    var = list(zip(xs, ws, dys))
    a = _report("vjp_1x1_conv_emitter", vjp_conv, var, args)
    b = _report("vjp_1x1_dot_general", vjp_dot, var, args)
    results["vjp_1x1_speedup_dot_over_conv"] = round(a["ms"] / b["ms"], 3)


def exp_dgrad_3x3(args, rng, results):
    dys, ws, xs = (_mk(rng, (B, HW, HW, C3)), _mk(rng, (3, 3, C3, C3)),
                   _mk(rng, (B, HW, HW, C3)))

    def dgrad_conv(dy, w, x):
        _, vjp = jax.vjp(lambda x_: conv_fwd(x_, w), x)
        return vjp(dy)[0]

    def dgrad_im2col(dy, w, x):
        # dx = full-correlation of dy with the spatially-flipped filter:
        # extract 3x3 patches of dy -> [B,H,W,9*C] then one dot with the
        # flipped filter reshaped [9*C, C]. Same math, matmul emitter.
        patches = jax.lax.conv_general_dilated_patches(
            dy, (3, 3), (1, 1), "SAME", dimension_numbers=DN)
        wf = jnp.flip(w, (0, 1))
        wr = jnp.transpose(wf, (3, 0, 1, 2)).reshape(9 * C3, C3)
        dx = jax.lax.dot_general(
            patches.reshape(-1, 9 * C3), wr, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dx.astype(dy.dtype).reshape(B, HW, HW, C3)

    print("== dgrad_3x3 64ch @56x56", flush=True)
    var = list(zip(dys, ws, xs))
    a = _report("dgrad_3x3_conv_emitter", dgrad_conv, var, args)
    b = _report("dgrad_3x3_im2col_dot", dgrad_im2col, var, args)
    np.testing.assert_allclose(
        np.asarray(dgrad_conv(*var[0]), np.float32),
        np.asarray(dgrad_im2col(*var[0]), np.float32),
        rtol=3e-2, atol=3e-1)
    results["dgrad_3x3_speedup_im2col_over_conv"] = round(
        a["ms"] / b["ms"], 3)


def exp_mixed_1x1(args, rng, results):
    """conv fwd + dot dgrad + conv wgrad via custom_vjp: each half routed
    to the emitter that won its isolated probe."""
    @jax.custom_vjp
    def conv1x1_mixed(x, w):
        return conv_fwd(x, w)

    def _fwd(x, w):
        return conv_fwd(x, w), (x, w)

    def _bwd(res, dy):
        x, w = res
        dy2 = dy.reshape(-1, Co)
        dx = jax.lax.dot_general(
            dy2, w.reshape(Ci, Co), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dy.dtype)
        dx = dx.reshape(B, HW, HW, Ci)
        _, vjp = jax.vjp(lambda w_: conv_fwd(x, w_), w)
        return dx, vjp(dy)[0]

    conv1x1_mixed.defvjp(_fwd, _bwd)

    xs, ws = _mk(rng, (B, HW, HW, Ci)), _mk(rng, (1, 1, Ci, Co))
    dys = [jnp.asarray(rng.rand(B, HW, HW, Co).astype("float32"))
           for _ in range(NVAR)]

    def mk_loss(fn):
        def run(x, w, dy):
            def loss(x_, w_):
                return jnp.sum(fn(x_, w_).astype(jnp.float32) * dy)
            v, g = jax.value_and_grad(loss, argnums=(0, 1))(x, w)
            return g[0]
        return run

    # parity first
    g1 = jax.grad(lambda x_: jnp.sum(conv_fwd(x_, ws[0])
                                     .astype(jnp.float32) * dys[0]))(xs[0])
    g2 = jax.grad(lambda x_: jnp.sum(conv1x1_mixed(x_, ws[0])
                                     .astype(jnp.float32) * dys[0]))(xs[0])
    np.testing.assert_allclose(np.asarray(g1, np.float32),
                               np.asarray(g2, np.float32),
                               rtol=2e-2, atol=2e-1)
    print("== mixed_1x1 fwd+bwd (conv fwd / dot dgrad / conv wgrad)",
          flush=True)
    var = list(zip(xs, ws, dys))
    a = _report("vjp_1x1_all_conv", mk_loss(conv_fwd), var, args)
    b = _report("vjp_1x1_mixed_emitter", mk_loss(conv1x1_mixed), var, args)
    results["mixed_1x1_speedup_over_conv"] = round(a["ms"] / b["ms"], 3)


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--exp", action="append", choices=EXPERIMENTS + ("all",),
                   help="experiment(s); default dgrad_1x1")
    p.add_argument("--timing", choices=sorted(TIMING), default="interleaved")
    p.add_argument("--iters", type=int, default=24)
    p.add_argument("--windows", type=int, default=4)
    args = p.parse_args()
    exps = args.exp or ["dgrad_1x1"]
    if "all" in exps:
        exps = list(EXPERIMENTS)

    print(json.dumps({"devices": [str(d) for d in jax.devices()],
                      "timing": args.timing}), flush=True)
    rng = np.random.RandomState(0)
    results = {}
    fns = {"dgrad_1x1": exp_dgrad_1x1, "vjp_1x1": exp_vjp_1x1,
           "dgrad_3x3": exp_dgrad_3x3, "mixed_1x1": exp_mixed_1x1}
    for e in exps:
        fns[e](args, rng, results)
    print(json.dumps({"exp": "dgrad_probe_summary", **results}), flush=True)


if __name__ == "__main__":
    main()
