"""VERDICT r4 #1: falsify-or-confirm the conv-backward irreducibility claim.

PROF_r04 §3 attributed +9.7 GB/step of flagship HBM traffic to XLA's conv
dgrad scheduling and declared it not program-reducible. This probe tests
that assertion on the worst-excess stage shapes from tools/attribute_bytes
(the [256,56,56,*] bottleneck convs; the single worst instruction is the
1x1 256<->64 dgrad fusion at 2.26 GB):

  A. 1x1 conv dgrad — XLA's conv emitter (what jax.vjp of
     conv_general_dilated lowers to) vs the SAME math as one dot_general
     ([B*H*W, Co] x [Co, Ci]): a 1x1 conv IS a matmul, so any emitter gap
     is pure scheduling waste.
  B. 3x3 conv dgrad — conv emitter vs an im2col formulation
     (conv_general_dilated_patches + dot), the verdict's suggested probe.
  C. the same A/B for the full fwd+bwd vjp of each conv (what the train
     step actually runs), since dgrad never runs un-fused in the step.

Each variant reports best-of-5 wall time and XLA cost-model bytes; the
verdict's decision rule: a >=10% win on the step-relevant variant ->
adopt + re-baseline the flagship; otherwise the MFU-0.29 roofline claim
stands TESTED.

    env PYTHONPATH=/root/.axon_site:/root/repo python tools/probe_dgrad.py
"""

from __future__ import annotations

import functools
import json
import time

import jax
import jax.numpy as jnp
import numpy as np

DN = ("NHWC", "HWIO", "NHWC")


def _time(fn, args, iters=30, windows=5):
    out = fn(*args)
    jax.block_until_ready(out)
    best = None
    for _ in range(windows):
        t0 = time.time()
        for _ in range(iters):
            out = fn(*args)
        jax.block_until_ready(out)
        dt = (time.time() - t0) / iters
        best = dt if best is None else min(best, dt)
    return best


def _cost(fn, args):
    ex = jax.jit(fn).lower(*args).compile()
    ca = ex.cost_analysis()
    ca = ca[0] if isinstance(ca, (list, tuple)) else (ca or {})
    return (float(ca.get("bytes accessed", 0.0)),
            float(ca.get("flops", 0.0)))


def _report(name, fn, args):
    jfn = jax.jit(fn)
    t = _time(jfn, args)
    b, f = _cost(fn, args)
    row = {"variant": name, "ms": round(t * 1e3, 3),
           "bytes_MB": round(b / 1e6, 1), "flops_G": round(f / 1e9, 2),
           "achieved_GBps": round(b / t / 1e9, 1) if b else None,
           "achieved_TFLOPs": round(f / t / 1e12, 2) if f else None}
    print(json.dumps(row), flush=True)
    return row


def conv_fwd(x, w, stride=1):
    return jax.lax.conv_general_dilated(
        x, w, window_strides=(stride, stride), padding="SAME",
        dimension_numbers=DN)


def main():
    rng = np.random.RandomState(0)
    results = {}

    # ---- A: 1x1 dgrad, the worst-excess instruction family --------------
    # forward: x [256,56,56,256] (*) w [1,1,256,64] -> y [256,56,56,64]
    # dgrad:   dy [256,56,56,64] -> dx [256,56,56,256]
    B, HW, Ci, Co = 256, 56, 256, 64
    dy = jnp.asarray(rng.rand(B, HW, HW, Co).astype("float32"),
                     jnp.bfloat16)
    w = jnp.asarray(rng.rand(1, 1, Ci, Co).astype("float32"), jnp.bfloat16)
    x = jnp.asarray(rng.rand(B, HW, HW, Ci).astype("float32"),
                    jnp.bfloat16)

    def dgrad_conv_1x1(dy, w):
        # exactly what jax emits for the vjp of a SAME 1x1 conv
        _, vjp = jax.vjp(lambda x_: conv_fwd(x_, w), x)
        return vjp(dy)[0]

    def dgrad_dot_1x1(dy, w):
        dy2 = dy.reshape(-1, Co)                     # [B*H*W, Co]
        w2 = w.reshape(Ci, Co)                       # [Ci, Co]
        dx = jax.lax.dot_general(dy2, w2, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        return dx.astype(dy.dtype).reshape(B, HW, HW, Ci)

    print("== A: 1x1 dgrad [256,56,56,64] -> [256,56,56,256]", flush=True)
    a_conv = _report("dgrad_1x1_conv_emitter", dgrad_conv_1x1, (dy, w))
    a_dot = _report("dgrad_1x1_dot_general", dgrad_dot_1x1, (dy, w))
    np.testing.assert_allclose(
        np.asarray(dgrad_conv_1x1(dy, w), np.float32),
        np.asarray(dgrad_dot_1x1(dy, w), np.float32), rtol=2e-2, atol=1e-2)
    results["dgrad_1x1_speedup_dot_over_conv"] = round(
        a_conv["ms"] / a_dot["ms"], 3)

    # ---- A': full vjp of the 1x1 conv (fwd + dgrad + wgrad) -------------
    def vjp_conv_1x1(x, w, dy):
        y, vjp = jax.vjp(lambda x_, w_: conv_fwd(x_, w_), x, w)
        return (y,) + vjp(dy)

    def vjp_dot_1x1(x, w, dy):
        x2 = x.reshape(-1, Ci)
        w2 = w.reshape(Ci, Co)
        dy2 = dy.reshape(-1, Co)

        def f(x2_, w2_):
            return jax.lax.dot_general(
                x2_, w2_, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32).astype(x2_.dtype)
        y2, vjp = jax.vjp(f, x2, w2)
        dx2, dw2 = vjp(dy2)
        return (y2.reshape(B, HW, HW, Co), dx2.reshape(B, HW, HW, Ci),
                dw2.reshape(1, 1, Ci, Co))

    print("== A': 1x1 fwd+bwd vjp", flush=True)
    av_conv = _report("vjp_1x1_conv_emitter", vjp_conv_1x1, (x, w, dy))
    av_dot = _report("vjp_1x1_dot_general", vjp_dot_1x1, (x, w, dy))
    results["vjp_1x1_speedup_dot_over_conv"] = round(
        av_conv["ms"] / av_dot["ms"], 3)

    # ---- B: 3x3 dgrad at 56x56, 64->64 ----------------------------------
    C3 = 64
    x3 = jnp.asarray(rng.rand(B, HW, HW, C3).astype("float32"),
                     jnp.bfloat16)
    w3 = jnp.asarray(rng.rand(3, 3, C3, C3).astype("float32"),
                     jnp.bfloat16)
    dy3 = jnp.asarray(rng.rand(B, HW, HW, C3).astype("float32"),
                      jnp.bfloat16)

    def dgrad_conv_3x3(dy, w):
        _, vjp = jax.vjp(lambda x_: conv_fwd(x_, w), x3)
        return vjp(dy)[0]

    def dgrad_im2col_3x3(dy, w):
        # dx = full-correlation of dy with the spatially-flipped filter:
        # extract 3x3 patches of dy -> [B,H,W,9*C] then one dot with the
        # flipped filter reshaped [9*C, C]. Same math, matmul emitter.
        patches = jax.lax.conv_general_dilated_patches(
            dy, (3, 3), (1, 1), "SAME", dimension_numbers=DN)
        wf = jnp.flip(w, (0, 1))                    # [3,3,Ci,Co]
        # dx[ci] = sum_{dh,dw,co} dy[h+dh,w+dw,co] * wf[dh,dw,ci,co]
        # patches channel layout from lax: [Cin_of_input=Co, 3, 3]
        wr = jnp.transpose(wf, (3, 0, 1, 2)).reshape(9 * C3, C3)
        dx = jax.lax.dot_general(
            patches.reshape(-1, 9 * C3), wr, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dx.astype(dy.dtype).reshape(B, HW, HW, C3)

    print("== B: 3x3 dgrad 64ch @56x56", flush=True)
    b_conv = _report("dgrad_3x3_conv_emitter", dgrad_conv_3x3, (dy3, w3))
    b_im2col = _report("dgrad_3x3_im2col_dot", dgrad_im2col_3x3, (dy3, w3))
    np.testing.assert_allclose(
        np.asarray(dgrad_conv_3x3(dy3, w3), np.float32),
        np.asarray(dgrad_im2col_3x3(dy3, w3), np.float32),
        rtol=3e-2, atol=3e-1)
    results["dgrad_3x3_speedup_im2col_over_conv"] = round(
        b_conv["ms"] / b_im2col["ms"], 3)

    print(json.dumps({"exp": "dgrad_probe_summary", **results}), flush=True)


if __name__ == "__main__":
    main()
