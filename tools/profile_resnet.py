"""ResNet-50 MFU attribution probes, consolidated (r12).

One flag-driven driver replacing the round-3/4 numbered copies
(profile_resnet{,2,3,4}.py), backed by the r12 observability API:
shape-byte parsing comes from `framework.costs.hlo_shape_bytes` (the one
copy), roofline verdicts from `framework.costs.roofline_fields`, and the
timed loops record "step" spans so the Chrome trace shows the same
intervals the JSON rows quote.

    python tools/profile_resnet.py --exp bench --batch_size 256
    python tools/profile_resnet.py --exp all

Experiments (--exp, repeatable):
  bench          pipelined step time + implied TFLOP/s (r02 baseline repro)
  overhead       per-call floor: identity over the same state pytree,
                 per-buffer vs per-byte split (one packed buffer)
  scan           K train steps fused into one lax.scan dispatch
  roofline       XLA cost-analysis bytes/flops -> HBM- vs MXU-bound verdict
  fwd_only       forward+loss only: is bwd disproportionately slow?
  conv_micro     stem 7x7/s2, space-to-depth variant, body 3x3 fwd+bwd
  hlo_bytes      per-opcode output-byte census of EVERY instruction line
  buffer_census  entry-computation-only census (real materialized buffers)
                 + biggest buffers with op_name metadata
"""

from __future__ import annotations

import argparse
import collections
import json
import re
import time

import numpy as np

EXPERIMENTS = ("bench", "overhead", "scan", "roofline", "fwd_only",
               "conv_micro", "hlo_bytes", "buffer_census")


def _realize(x):
    """Trusted barrier on the tunnel: host-value realization."""
    return float(np.asarray(x).ravel()[0])


def _build_train(batch, rng):
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu import models

    pt.reset_default_programs()
    pt.reset_global_scope()
    with pt.core.unique_name.guard():
        loss, acc, _ = models.resnet.resnet_imagenet(
            depth=50, is_test=False, data_format="NHWC", use_bf16=True)
        opt = pt.optimizer.MomentumOptimizer(learning_rate=3e-3,
                                             momentum=0.9)
        opt.minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    feed = {
        "img": jnp.asarray(rng.rand(batch, 224, 224, 3).astype("float32")),
        "label": jnp.asarray(rng.randint(0, 1000,
                                         (batch, 1)).astype("int64")),
    }
    return exe, loss, feed


def _compiled_executable(exe, loss, feed):
    import jax.numpy as jnp
    import paddle_tpu as pt
    compiled = exe._lookup_or_compile(
        pt.default_main_program(), feed, [loss.name], pt.global_scope())
    scope = pt.global_scope()
    feed_vals = tuple(jnp.asarray(feed[n]) for n in compiled.feed_names)
    ro_vals = tuple(scope.get(n) for n in compiled.ro_names)
    rw_vals = tuple(scope.get(n) for n in compiled.rw_names)
    return compiled.fn.lower(feed_vals, ro_vals, rw_vals,
                             np.uint32(0)).compile()


def exp_bench(args, rng):
    from paddle_tpu.observability import tracing
    exe, loss, feed = _build_train(args.batch_size, rng)
    out = exe.run(feed=feed, fetch_list=[loss], return_numpy=False)
    _realize(out[0])
    t0 = time.time()
    fetched = []
    with tracing.span("user", f"profile_resnet/bench_bs{args.batch_size}"):
        for _ in range(args.iters):
            out = exe.run(feed=feed, fetch_list=[loss], return_numpy=False)
            fetched.append(out[0])
        _realize(fetched[-1])
    dt = time.time() - t0
    ca = exe.cost_analysis(feed=feed, fetch_list=[loss])
    flops = float(ca.get("flops", 0.0)) if ca else 0.0
    print(json.dumps({
        "exp": f"resnet_bs{args.batch_size}",
        "step_ms": round(dt / args.iters * 1e3, 2),
        "imgs_per_sec": round(args.batch_size * args.iters / dt, 1),
        "flops_per_step": flops,
        "implied_tflops": round(flops * args.iters / dt / 1e12, 1),
    }), flush=True)
    return exe


def exp_overhead(args, rng):
    """Per-call floor: identity-ish update over the SAME state buffers the
    train step carries, with ~zero FLOPs; then the same bytes in ONE
    buffer (per-buffer vs per-byte overhead split)."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt

    _build_train(args.batch_size, rng)
    scope = pt.global_scope()
    state = [scope.get(n) for n in sorted(scope.local_var_names())]
    state = [s for s in state if hasattr(s, "dtype")]
    n_buffers = len(state)
    n_bytes = int(sum(np.prod(s.shape) * s.dtype.itemsize for s in state))

    @jax.jit
    def ident(xs):
        return [x + jnp.ones((), x.dtype) for x in xs]

    out = ident(state)
    _realize(out[0])
    t0 = time.time()
    for _ in range(20):
        out = ident(out)
    _realize(out[0])
    print(json.dumps({
        "exp": "overhead_identity",
        "step_ms": round((time.time() - t0) / 20 * 1e3, 2),
        "n_buffers": n_buffers, "mbytes": round(n_bytes / 1e6, 1),
    }), flush=True)

    big = jnp.zeros(n_bytes // 4, jnp.float32)

    @jax.jit
    def ident1(x):
        return x + 1.0

    out = ident1(big)
    _realize(out)
    t0 = time.time()
    for _ in range(20):
        out = ident1(out)
    _realize(out)
    print(json.dumps({
        "exp": "overhead_packed",
        "step_ms": round((time.time() - t0) / 20 * 1e3, 2),
        "n_buffers": 1, "mbytes": round(n_bytes / 1e6, 1),
    }), flush=True)


def exp_scan(args, rng):
    """K train steps per XLA execution via lax.scan over stacked batches
    (uint8-staged images cast+scaled on device)."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt

    batch, k = args.batch_size, args.scan_k
    exe, loss, _ = _build_train(batch, rng)
    prog, scope = pt.default_main_program(), pt.global_scope()
    compiled = exe._lookup_or_compile(
        prog,
        {"img": np.zeros((batch, 224, 224, 3), np.float32),
         "label": np.zeros((batch, 1), np.int64)},
        [loss.name], scope)

    imgs = jnp.asarray(rng.randint(
        0, 255, (k, batch, 224, 224, 3)).astype(np.uint8))
    labels = jnp.asarray(rng.randint(0, 1000, (k, batch, 1)).astype("int64"))
    ro_vals = tuple(scope.get(n) for n in compiled.ro_names)
    rw0 = tuple(scope.get(n) for n in compiled.rw_names)
    rw_out_idx = [compiled.state_out_names.index(n)
                  for n in compiled.rw_names]

    def one(rw_vals, xs):
        img_u8, lab = xs
        img = img_u8.astype(jnp.float32) / 255.0
        fetches, new_state = compiled.fn.__wrapped__(
            (img, lab), ro_vals, rw_vals, np.uint32(1))
        return tuple(new_state[i] for i in rw_out_idx), fetches[0]

    @jax.jit
    def loop(rw_vals, imgs, labels):
        return jax.lax.scan(one, rw_vals, (imgs, labels))

    rw, losses = loop(rw0, imgs, labels)
    _realize(losses[-1])
    outer = 3
    t0 = time.time()
    for _ in range(outer):
        rw, losses = loop(rw, imgs, labels)
    _realize(losses[-1])
    dt = time.time() - t0
    print(json.dumps({
        "exp": f"resnet_scan{k}_bs{batch}",
        "step_ms": round(dt / (outer * k) * 1e3, 2),
        "imgs_per_sec": round(batch * k * outer / dt, 1),
        "loss_first": round(float(losses[0]), 3),
        "loss_last": round(float(losses[-1]), 3),
    }), flush=True)


def exp_roofline(args, rng):
    from paddle_tpu.framework.costs import roofline_fields
    exe, loss, feed = _build_train(args.batch_size, rng)
    ca = exe.cost_analysis(feed=feed, fetch_list=[loss])
    flops = float(ca.get("flops", 0.0))
    baw = float(ca.get("bytes accessed", 0.0))
    out = exe.run(feed=feed, fetch_list=[loss], return_numpy=False)
    _realize(out[0])
    t0 = time.time()
    for _ in range(args.iters):
        out = exe.run(feed=feed, fetch_list=[loss], return_numpy=False)
    _realize(out[0])
    step_s = (time.time() - t0) / args.iters
    print(json.dumps({
        "exp": "roofline_train_step",
        "bytes_accessed_output": float(
            ca.get("bytes accessed output", 0.0)),
        **roofline_fields(step_s, flops, baw),
    }), flush=True)


def exp_fwd_only(args, rng):
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu import models

    pt.reset_default_programs()
    pt.reset_global_scope()
    with pt.core.unique_name.guard():
        loss, acc, _ = models.resnet.resnet_imagenet(
            depth=50, is_test=False, data_format="NHWC", use_bf16=True)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    feed = {
        "img": jnp.asarray(rng.rand(args.batch_size, 224, 224,
                                    3).astype("float32")),
        "label": jnp.asarray(rng.randint(
            0, 1000, (args.batch_size, 1)).astype("int64")),
    }
    out = exe.run(feed=feed, fetch_list=[loss], return_numpy=False)
    _realize(out[0])
    t0 = time.time()
    for _ in range(args.iters):
        out = exe.run(feed=feed, fetch_list=[loss], return_numpy=False)
    _realize(out[0])
    dt = (time.time() - t0) / args.iters
    ca = exe.cost_analysis(feed=feed, fetch_list=[loss])
    f2 = float(ca.get("flops", 0.0))
    print(json.dumps({
        "exp": f"fwd_only_bs{args.batch_size}",
        "step_ms": round(dt * 1e3, 2), "flops": f2,
        "implied_tflops": round(f2 / dt / 1e12, 1),
    }), flush=True)


def _conv_micro(name, x_shape, k_shape, stride, padding):
    import jax
    import jax.numpy as jnp
    from paddle_tpu.framework.costs import V5E_PEAK_TFLOPS

    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(*x_shape).astype(np.float32), jnp.bfloat16)
    k = jnp.asarray(rng.randn(*k_shape).astype(np.float32), jnp.bfloat16)

    def f(x, k):
        out = jax.lax.conv_general_dilated(
            x, k, (stride, stride), padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        return jnp.sum(out.astype(jnp.float32))

    g = jax.jit(jax.grad(f, argnums=(0, 1)))
    out = g(x, k)
    _realize(out[0])
    t0 = time.time()
    for _ in range(10):
        out = g(x, k)
    _realize(out[0])
    dt = (time.time() - t0) / 10
    n, h, w, _ = x_shape
    kh, kw, ci, co = k_shape
    oh = (h + sum(padding[0]) - kh) // stride + 1
    ow = (w + sum(padding[1]) - kw) // stride + 1
    flops = 3 * 2 * n * oh * ow * kh * kw * ci * co  # fwd + 2 bwd convs
    print(json.dumps({
        "exp": name, "ms": round(dt * 1e3, 2),
        "tflops_attained": round(flops / dt / 1e12, 1),
        "pct_peak": round(flops / dt / V5E_PEAK_TFLOPS / 10.0, 1),
    }), flush=True)


def exp_conv_micro(args, rng):
    b = args.batch_size
    _conv_micro("stem_conv7x7s2_c3", (b, 224, 224, 3), (7, 7, 3, 64), 2,
                ((3, 3), (3, 3)))
    _conv_micro("stem_s2d_conv4x4s1_c12", (b, 112, 112, 12),
                (4, 4, 12, 64), 1, ((1, 2), (1, 2)))
    _conv_micro("body_conv3x3_c128", (b, 28, 28, 128), (3, 3, 128, 128), 1,
                ((1, 1), (1, 1)))
    _conv_micro("body_conv3x3_c256_14", (b, 14, 14, 256),
                (3, 3, 256, 256), 1, ((1, 1), (1, 1)))


def _dump_hlo(args, rng):
    exe, loss, feed = _build_train(args.batch_size, rng)
    ex = _compiled_executable(exe, loss, feed)
    hlo = ex.as_text()
    with open("/tmp/resnet_train_optimized.hlo", "w") as f:
        f.write(hlo)
    return hlo, ex


def exp_hlo_bytes(args, rng):
    """Per-opcode output-byte census over EVERY instruction line (includes
    fusion-internal lines that never touch HBM — see buffer_census for the
    materialized-only view)."""
    from paddle_tpu.framework.costs import hlo_shape_bytes
    hlo, ex = _dump_hlo(args, rng)
    op_bytes = collections.Counter()
    op_count = collections.Counter()
    big_f32 = []
    for line in hlo.splitlines():
        m = re.search(r"=\s+([a-z0-9]+\[[0-9,]*\][^ ]*)\s+([a-z\-]+)", line)
        if not m:
            continue
        sh, op = m.group(1), m.group(2)
        try:
            b = hlo_shape_bytes(sh)
        except ValueError:
            continue
        op_bytes[op] += b
        op_count[op] += 1
        if sh.startswith("f32") and b > 50e6:
            big_f32.append((round(b / 1e6), op, line.strip()[:140]))
    print(json.dumps({
        "exp": "hlo_output_bytes_by_op",
        "top": [(op, round(b / 1e9, 2), op_count[op])
                for op, b in op_bytes.most_common(15)],
    }), flush=True)
    big_f32.sort(reverse=True)
    print(json.dumps({"exp": "big_f32_buffers",
                      "top10": big_f32[:10]}), flush=True)
    ca = ex.cost_analysis()
    ca = ca[0] if isinstance(ca, (list, tuple)) else (ca or {})
    keys = {k: v for k, v in ca.items()
            if "bytes" in k and isinstance(v, float) and v > 1e9}
    print(json.dumps({"exp": "cost_analysis_byte_keys", "keys": keys}),
          flush=True)


def exp_buffer_census(args, rng):
    """Entry-computation-only census: top-level instructions of the
    compiled module — the ones whose outputs are real HBM buffers —
    bucketed by opcode and dtype, plus the biggest buffers w/ metadata."""
    from paddle_tpu.framework.costs import hlo_shape_bytes
    hlo, ex = _dump_hlo(args, rng)
    cur_comp = None
    entry_ops = []
    for line in hlo.splitlines():
        mc = re.match(r"(ENTRY )?%?([\w.\-]+)\s*\([^)]*\)\s*->", line)
        if mc:
            cur_comp = ("ENTRY" if mc.group(1) else mc.group(2))
            continue
        if cur_comp != "ENTRY":
            continue
        m = re.match(r"\s+%?([\w.\-]+)\s*=\s*(\S+)\s+([a-z\-]+)", line)
        if not m:
            continue
        name, sh, op = m.groups()
        try:
            b = hlo_shape_bytes(sh)
        except ValueError:
            b = 0
        mm = re.search(r'op_name="([^"]*)"', line)
        entry_ops.append((b, op, sh, name, mm.group(1) if mm else ""))

    op_bytes = collections.Counter()
    op_count = collections.Counter()
    dtype_bytes = collections.Counter()
    for b, op, sh, name, meta in entry_ops:
        op_bytes[op] += b
        op_count[op] += 1
        md = re.match(r"([a-z0-9]+)\[", sh)
        if md:
            dtype_bytes[md.group(1)] += b
    print(json.dumps({
        "exp": "entry_output_bytes_by_op",
        "total_GB": round(sum(op_bytes.values()) / 1e9, 2),
        "top": [(op, round(bb / 1e9, 2), op_count[op])
                for op, bb in op_bytes.most_common(18)],
        "by_dtype_GB": {d: round(bb / 1e9, 2)
                        for d, bb in dtype_bytes.most_common()},
    }), flush=True)
    big = sorted(entry_ops, reverse=True)[:20]
    print(json.dumps({
        "exp": "biggest_entry_buffers",
        "top20": [(round(b / 1e6), op, sh[:48], meta[:90])
                  for b, op, sh, name, meta in big],
    }), flush=True)


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--exp", action="append", choices=EXPERIMENTS + ("all",),
                   help="experiment(s) to run; default bench")
    p.add_argument("--batch_size", type=int, default=256)
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--scan_k", type=int, default=8,
                   help="scan: train steps fused per dispatch")
    args = p.parse_args()
    exps = args.exp or ["bench"]
    if "all" in exps:
        exps = list(EXPERIMENTS)

    import jax
    print(json.dumps({"devices": [str(d) for d in jax.devices()]}),
          flush=True)
    rng = np.random.RandomState(0)
    fns = {"bench": exp_bench, "overhead": exp_overhead, "scan": exp_scan,
           "roofline": exp_roofline, "fwd_only": exp_fwd_only,
           "conv_micro": exp_conv_micro, "hlo_bytes": exp_hlo_bytes,
           "buffer_census": exp_buffer_census}
    for e in exps:
        fns[e](args, np.random.RandomState(0) if e != "bench" else rng)


if __name__ == "__main__":
    main()
