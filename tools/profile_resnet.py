"""Attribution experiment for the ResNet-50 MFU gap (round-3, VERDICT #1).

Prints one JSON line per experiment. Run on the real TPU:

    env PYTHONPATH=/root/.axon_site:/root/repo python tools/profile_resnet.py

Experiments:
  resnet_bs256        pipelined step time (round-2 baseline reproduction)
  resnet_bs512        does a bigger batch amortize per-step overhead?
  overhead_identity   jit call with the SAME state pytree (~320 buffers,
                      ~200 MB) but ~zero FLOPs -> per-call floor from
                      dispatch + per-buffer handling through the tunnel
  overhead_packed     same bytes in ONE buffer -> per-buffer vs per-byte
  resnet_scan8        8 train steps fused into one lax.scan call ->
                      amortizes every per-call cost; the in-graph loop
                      the reference gets from py_reader+executor loop
                      (reference layers/io.py:474)
"""

from __future__ import annotations

import json
import time

import numpy as np


def _realize(x):
    """Trusted barrier on the tunnel: host-value realization."""
    return float(np.asarray(x).ravel()[0])


def bench_resnet(batch, iters=20):
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu import models

    pt.reset_default_programs()
    pt.reset_global_scope()
    loss, acc, _ = models.resnet.resnet_imagenet(
        depth=50, is_test=False, data_format="NHWC", use_bf16=True)
    opt = pt.optimizer.MomentumOptimizer(learning_rate=3e-3, momentum=0.9)
    opt.minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())

    rng = np.random.RandomState(0)
    feed = {
        "img": jnp.asarray(rng.rand(batch, 224, 224, 3).astype("float32")),
        "label": jnp.asarray(rng.randint(0, 1000, (batch, 1)).astype("int64")),
    }
    out = exe.run(feed=feed, fetch_list=[loss], return_numpy=False)
    _realize(out[0])
    t0 = time.time()
    fetched = []
    for _ in range(iters):
        out = exe.run(feed=feed, fetch_list=[loss], return_numpy=False)
        fetched.append(out[0])
    _realize(fetched[-1])
    dt = time.time() - t0
    ca = exe.cost_analysis(feed=feed, fetch_list=[loss])
    flops = float(ca.get("flops", 0.0)) if ca else 0.0
    print(json.dumps({
        "exp": f"resnet_bs{batch}", "step_ms": round(dt / iters * 1e3, 2),
        "imgs_per_sec": round(batch * iters / dt, 1),
        "flops_per_step": flops,
        "implied_tflops": round(flops * iters / dt / 1e12, 1),
    }), flush=True)
    return exe, loss, feed


def bench_overhead(exe):
    """Per-call floor: identity-ish update over the SAME state buffers the
    train step carries, with ~zero FLOPs."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt

    scope = pt.global_scope()
    names = sorted(n for n in scope.local_var_names())
    state = [scope.get(n) for n in names]
    state = [s for s in state if hasattr(s, "dtype")]
    n_buffers = len(state)
    n_bytes = int(sum(np.prod(s.shape) * s.dtype.itemsize for s in state))

    @jax.jit
    def ident(xs):
        return [x + jnp.ones((), x.dtype) for x in xs]

    out = ident(state)
    _realize(out[0])
    t0 = time.time()
    for _ in range(20):
        out = ident(out)
    _realize(out[0])
    dt = (time.time() - t0) / 20
    print(json.dumps({
        "exp": "overhead_identity", "step_ms": round(dt * 1e3, 2),
        "n_buffers": n_buffers, "mbytes": round(n_bytes / 1e6, 1),
    }), flush=True)

    # same bytes, ONE buffer
    big = jnp.zeros(n_bytes // 4, jnp.float32)

    @jax.jit
    def ident1(x):
        return x + 1.0

    out = ident1(big)
    _realize(out)
    t0 = time.time()
    for _ in range(20):
        out = ident1(out)
    _realize(out)
    dt = (time.time() - t0) / 20
    print(json.dumps({
        "exp": "overhead_packed", "step_ms": round(dt * 1e3, 2),
        "n_buffers": 1, "mbytes": round(n_bytes / 1e6, 1),
    }), flush=True)


def bench_scan(batch=256, k=8, outer=3):
    """K train steps per XLA execution via lax.scan over stacked batches."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu import models

    pt.reset_default_programs()
    pt.reset_global_scope()
    loss, acc, _ = models.resnet.resnet_imagenet(
        depth=50, is_test=False, data_format="NHWC", use_bf16=True)
    opt = pt.optimizer.MomentumOptimizer(learning_rate=3e-3, momentum=0.9)
    opt.minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())

    prog = pt.default_main_program()
    scope = pt.global_scope()
    compiled = exe._lookup_or_compile(
        prog,
        {"img": np.zeros((batch, 224, 224, 3), np.float32),
         "label": np.zeros((batch, 1), np.int64)},
        [loss.name], scope)

    rng = np.random.RandomState(0)
    # uint8-staged images, cast+scale on device inside the scanned step
    imgs = jnp.asarray(rng.randint(0, 255, (k, batch, 224, 224, 3),
                                   ).astype(np.uint8))
    labels = jnp.asarray(rng.randint(0, 1000, (k, batch, 1)).astype("int64"))

    ro_names, rw_names = compiled.ro_names, compiled.rw_names
    ro_vals = tuple(scope.get(n) for n in ro_names)
    rw0 = tuple(scope.get(n) for n in rw_names)
    state_out_names = compiled.state_out_names
    rw_out_idx = [state_out_names.index(n) for n in rw_names]

    def one(rw_vals, xs):
        img_u8, lab = xs
        img = img_u8.astype(jnp.float32) / 255.0
        fetches, new_state = compiled.fn.__wrapped__(
            (img, lab), ro_vals, rw_vals, np.uint32(1))
        new_rw = tuple(new_state[i] for i in rw_out_idx)
        return new_rw, fetches[0]

    @jax.jit
    def loop(rw_vals, imgs, labels):
        return jax.lax.scan(one, rw_vals, (imgs, labels))

    rw, losses = loop(rw0, imgs, labels)
    _realize(losses[-1])
    t0 = time.time()
    for _ in range(outer):
        rw, losses = loop(rw, imgs, labels)
    _realize(losses[-1])
    dt = time.time() - t0
    print(json.dumps({
        "exp": f"resnet_scan{k}_bs{batch}",
        "step_ms": round(dt / (outer * k) * 1e3, 2),
        "imgs_per_sec": round(batch * k * outer / dt, 1),
        "loss_first": round(float(losses[0]), 3),
        "loss_last": round(float(losses[-1]), 3),
    }), flush=True)


def main():
    import jax
    print(json.dumps({"devices": [str(d) for d in jax.devices()]}),
          flush=True)
    exe, loss, feed = bench_resnet(256)
    bench_overhead(exe)
    del exe, loss, feed
    bench_resnet(512, iters=10)
    bench_scan(256, k=8)


if __name__ == "__main__":
    main()
