"""Attribute the DeepFM sparse train step's time on the TPU.

Builds the driver-config-#5 step (bs4096, vocab 1M, 39 fields, is_sparse),
dumps the optimized HLO, and ranks top-level instructions by the conv/fusion
backend_config's own `estimated_cycles`, bucketing by op_name metadata. Also
times the step and prints cost-analysis totals.

    env PYTHONPATH=/root/.axon_site:/root/repo python tools/probe_deepfm.py
"""

from __future__ import annotations

import collections
import json
import re
import time

import numpy as np


def build(b=4096, vocab=1000000, sparse=True, row_pad=None):
    import paddle_tpu as pt
    from paddle_tpu.models import deepfm

    pt.reset_default_programs()
    pt.reset_global_scope()
    rng = np.random.RandomState(0)
    with pt.core.unique_name.guard():
        loss, _ = deepfm.deepfm(num_fields=39, vocab_size=vocab,
                                is_sparse=sparse, row_pad=row_pad)
        opt = pt.optimizer.AdamOptimizer(learning_rate=3e-4)
        opt.minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    import jax.numpy as jnp
    feed = {"feat_ids": jnp.asarray(
                rng.randint(0, vocab, (b, 39)).astype("int64")),
            "feat_vals": jnp.asarray(rng.rand(b, 39).astype("float32")),
            "label": jnp.asarray(
                rng.randint(0, 2, (b, 1)).astype("float32"))}
    return exe, loss, feed, pt.default_main_program(), pt.global_scope()


def analyze(tag, sparse, row_pad=None):
    import jax.numpy as jnp

    exe, loss, feed, prog, scope = build(sparse=sparse, row_pad=row_pad)
    compiled = exe._lookup_or_compile(prog, feed, [loss.name], scope)
    feed_vals = tuple(jnp.asarray(feed[n]) for n in compiled.feed_names)
    ro_vals = tuple(scope.get(n) for n in compiled.ro_names)
    rw_vals = tuple(scope.get(n) for n in compiled.rw_names)
    ex = compiled.fn.lower(feed_vals, ro_vals, rw_vals,
                           np.uint32(0)).compile()
    hlo = ex.as_text()
    with open(f"/tmp/deepfm_{tag}.hlo", "w") as f:
        f.write(hlo)

    rows = []
    for line in hlo.splitlines():
        mcy = re.search(r'"estimated_cycles":"(\d+)"', line)
        if not mcy:
            continue
        cyc = int(mcy.group(1))
        mop = re.match(r"\s+%?([\w.\-]+)\s*=", line)
        mmeta = re.search(r'op_name="([^"]*)"', line)
        rows.append((cyc, mop.group(1) if mop else "?",
                     mmeta.group(1)[:90] if mmeta else ""))
    rows.sort(reverse=True)
    total_cyc = sum(r[0] for r in rows)

    buckets = collections.Counter()
    for cyc, name, meta in rows:
        key = "other"
        for pat in ("sort", "scatter", "gather", "dot", "reduce",
                    "transpose", "convert", "iota", "unique", "while",
                    "dynamic"):
            if pat in name or pat in meta.lower():
                key = pat
                break
        buckets[key] += cyc
    out = {
        "tag": tag,
        "est_total_Mcycles": round(total_cyc / 1e6, 1),
        "by_bucket_Mcycles": {k: round(v / 1e6, 1)
                              for k, v in buckets.most_common()},
        "top12": [(round(c / 1e6, 2), n, m) for c, n, m in rows[:12]],
    }

    o = exe.run(feed=feed, fetch_list=[loss], return_numpy=False)
    float(np.asarray(o[0]).ravel()[0])
    best = None
    for _ in range(3):
        t0 = time.time()
        fetched = []
        for _ in range(10):
            o = exe.run(feed=feed, fetch_list=[loss], return_numpy=False)
            fetched.append(o[0])
        float(np.asarray(fetched[-1]).ravel()[0])
        dt = (time.time() - t0) / 10
        best = dt if best is None else min(best, dt)
    out["step_ms"] = round(best * 1e3, 2)
    ca = ex.cost_analysis()
    ca = ca[0] if isinstance(ca, (list, tuple)) else ca
    out["bytes_GB"] = round(float(ca.get("bytes accessed", 0)) / 1e9, 3)
    out["flops_G"] = round(float(ca.get("flops", 0)) / 1e9, 1)
    print(json.dumps(out), flush=True)


def main():
    analyze("sparse_pad128", True, row_pad=128)
    analyze("dense_pad128", False, row_pad=128)


if __name__ == "__main__":
    main()
