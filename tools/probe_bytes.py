"""Quick probe: compile the flagship train step on the TPU and report XLA
cost-analysis bytes-accessed/flops + a short timed window.

Usage: env PYTHONPATH=/root/.axon_site:/root/repo python tools/probe_bytes.py
"""
import json
import sys
import time

import numpy as np


def main(batch=256, iters=10):
    import jax.numpy as jnp

    sys.path.insert(0, "/root/repo")
    import bench

    exe, loss = bench._build_resnet_train(batch)
    rng = np.random.RandomState(0)
    feed = {
        "img": jnp.asarray(rng.rand(batch, 224, 224, 3).astype("float32")),
        "label": jnp.asarray(
            rng.randint(0, 1000, (batch, 1)).astype("int64")),
    }
    out = exe.run(feed=feed, fetch_list=[loss], return_numpy=False)
    float(out[0])
    ca = exe.cost_analysis(feed=feed, fetch_list=[loss])
    flops = float(ca.get("flops", 0.0)) if ca else 0.0
    bytes_acc = float(ca.get("bytes accessed", 0.0)) if ca else 0.0

    best = None
    losses = []
    for _ in range(3):
        fetched = []
        t0 = time.time()
        for _ in range(iters):
            out = exe.run(feed=feed, fetch_list=[loss], return_numpy=False)
            fetched.append(out[0])
        float(fetched[-1])
        dt = time.time() - t0
        best = dt if best is None else min(best, dt)
        losses.extend(float(x) for x in fetched)
    step_ms = best / iters * 1e3
    imgs_s = batch / (best / iters)
    print(json.dumps({
        "bytes_accessed_xla": bytes_acc,
        "bytes_GB": round(bytes_acc / 1e9, 2),
        "flops_per_step": flops,
        "step_ms": round(step_ms, 1),
        "images_per_sec": round(imgs_s, 1),
        "implied_tflops": round(flops / (best / iters) / 1e12, 2),
        "mfu_v5e": round(flops / (best / iters) / 197e12, 4),
        "ideal_hbm_ms": round(bytes_acc / 819e9 * 1e3, 1),
        "loss_first": round(losses[0], 4),
        "loss_last": round(losses[-1], 4),
    }))


if __name__ == "__main__":
    main()
