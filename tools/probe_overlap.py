#!/usr/bin/env python
"""Comm/compute overlap census for the data-parallel gradient pipeline
(ISSUE r8): decompose profiler trace spans into comm-exposed vs
comm-overlapped time per step, and A/B gradient bucketing (the reference's
fuse_all_reduce capability) against one-collective-per-gradient.

Method. The XLA:CPU thunk executor emits one device-category trace span
per HLO instruction (reduce-scatter.N / all-gather.N / all-to-all.N /
fusions / dots ...). For a traced window of steps we merge, across the
whole process timeline:

  comm      = union of collective spans
  compute   = union of every other device-category span
  exposed   = |comm \\ compute|   (collective time nothing computes under)
  overlapped= |comm ∩ compute|   (collective time hidden under compute)

per step = totals / traced iters. Two configs:

  wide_mlp    784->2048->2048->10 (23 MB of gradients, comm-heavy): the
              allreduce / reduce_scatter / quantized mode comparison.
  deep_narrow 20 layers of fc(63->63) (40+ tiny gradients, none
              dp-divisible): the bucketed vs unbucketed A/B — bucketing
              coalesces the whole tail into ONE transfer per phase.

Caveat (stated in the artifact): the "devices" are 8 XLA host-platform
threads sharing this box's cores, so overlap reflects the host threadpool
schedule, not an ICI/DMA engine; byte/structure claims are exact, the
ms decomposition is a CPU-mesh census to be re-run on TPU hardware.

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python tools/probe_overlap.py | tee PROBE_OVERLAP_r08.json
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

if "XLA_FLAGS" not in os.environ:
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"

from probe_common import census_wire_bytes, collective_census  # noqa: E402

_COMM_PREFIXES = ("all-reduce", "all-gather", "reduce-scatter",
                  "all-to-all", "collective-permute")
ITERS = 12
WINDOWS = 3


def _merge(intervals):
    out = []
    for s, e in sorted(intervals):
        if out and s <= out[-1][1]:
            out[-1][1] = max(out[-1][1], e)
        else:
            out.append([s, e])
    return out


def _measure(mlen):
    return sum(e - s for s, e in mlen)


def _intersect_len(a, b):
    i = j = 0
    total = 0.0
    while i < len(a) and j < len(b):
        s = max(a[i][0], b[j][0])
        e = min(a[i][1], b[j][1])
        if e > s:
            total += e - s
        if a[i][1] < b[j][1]:
            i += 1
        else:
            j += 1
    return total


def overlap_census(trace_dir, iters):
    """exposed / overlapped comm ms per step from the span timeline."""
    from paddle_tpu.profiler import _collect_device_trace_events
    comm, compute = [], []
    comm_by_kind = {}
    for ev in _collect_device_trace_events(trace_dir):
        if ev.get("cat") != "device" or ev.get("dur", 0) <= 0:
            continue
        if not isinstance(ev.get("args"), dict) or \
                "hlo_op" not in ev["args"]:
            continue
        name = str(ev.get("name", ""))
        span = (ev["ts"], ev["ts"] + ev["dur"])
        kind = next((p for p in _COMM_PREFIXES if name.startswith(p)), None)
        if kind:
            comm.append(span)
            comm_by_kind[kind] = comm_by_kind.get(kind, 0.0) + ev["dur"]
        else:
            compute.append(span)
    mcomm, mcompute = _merge(comm), _merge(compute)
    comm_len = _measure(mcomm)
    overlapped = _intersect_len(mcomm, mcompute)
    exposed = comm_len - overlapped
    return {
        "n_comm_spans": len(comm),
        "comm_span_ms_per_step": round(sum(comm_by_kind.values())
                                       / 1e3 / iters, 3),
        "comm_span_ms_by_kind": {k: round(v / 1e3 / iters, 3)
                                 for k, v in sorted(comm_by_kind.items())},
        "comm_exposed_ms_per_step": round(exposed / 1e3 / iters, 3),
        "comm_overlapped_ms_per_step": round(overlapped / 1e3 / iters, 3),
        "overlapped_fraction": round(overlapped / comm_len, 3)
        if comm_len else None,
    }


def _build(config):
    import paddle_tpu as pt
    from paddle_tpu import layers

    pt.reset_default_programs()
    pt.reset_global_scope()
    with pt.core.unique_name.guard():
        if config == "wide_mlp":
            x = layers.data("x", shape=[784])
            h = layers.fc(x, size=2048, act="relu")
            h = layers.fc(h, size=2048, act="relu")
            logits = layers.fc(h, size=10)
        else:                                   # deep_narrow
            x = layers.data("x", shape=[63])
            h = x
            for _ in range(20):
                h = layers.fc(h, size=63, act="relu")
            logits = layers.fc(h, size=10)
        label = layers.data("label", shape=[1], dtype="int64")
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        pt.optimizer.MomentumOptimizer(0.05, momentum=0.9).minimize(loss)
    return loss


def _feed(config, rng):
    d = 784 if config == "wide_mlp" else 63
    return {"x": rng.rand(64, d).astype("float32"),
            "label": rng.randint(0, 10, (64, 1)).astype("int64")}


def run_variant(config, mode, bucket_bytes=None):
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.parallel import ParallelExecutor, grad_comm
    from paddle_tpu.parallel.strategy import BuildStrategy, ReduceStrategy

    loss = _build(config)
    bst = BuildStrategy()
    bst.reduce_strategy = {"allreduce": ReduceStrategy.AllReduce,
                           "reduce_scatter": ReduceStrategy.ReduceScatter,
                           "quantized": ReduceStrategy.ReduceScatter,
                           }[mode]
    if mode == "quantized":
        bst.quant_comm = "int8"
    if bucket_bytes is not None:
        bst.comm_bucket_bytes = bucket_bytes
    exe = ParallelExecutor(loss_name=loss.name, build_strategy=bst)
    pt.Executor().run(pt.default_startup_program())
    feed = _feed(config, np.random.RandomState(0))

    def step():
        return exe.run(feed=feed, fetch_list=[loss], return_numpy=False)

    float(np.asarray(step()[0]))            # compile + drain
    best = None
    for _ in range(WINDOWS):
        t0 = time.time()
        outs = [step() for _ in range(ITERS)]
        float(np.asarray(outs[-1][0]))
        dt = (time.time() - t0) / ITERS * 1e3
        best = dt if best is None else min(best, dt)
    spreads = []
    for _ in range(WINDOWS):
        t0 = time.time()
        outs = [step() for _ in range(ITERS)]
        float(np.asarray(outs[-1][0]))
        spreads.append(round((time.time() - t0) / ITERS * 1e3, 3))

    trace_dir = tempfile.mkdtemp(prefix=f"ptpu_ov_{config}_{mode}_")
    jax.profiler.start_trace(trace_dir)
    outs = [step() for _ in range(ITERS)]
    float(np.asarray(outs[-1][0]))
    jax.profiler.stop_trace()
    ov = overlap_census(trace_dir, ITERS)
    shutil.rmtree(trace_dir, ignore_errors=True)

    # structural side: the compiled collectives + wire bytes
    scope = pt.global_scope()
    cs = list(exe._cache.values())[-1]
    feed_vals = tuple(jnp.asarray(feed[n]) for n in cs.feed_names)
    ro = tuple(scope.get(n) for n in cs.ro_names)
    rw = tuple(scope.get(n) for n in cs.rw_names)
    hlo = cs.fn.lower(feed_vals, ro, rw, np.uint32(0)).compile().as_text()
    census = collective_census(hlo)
    n_grad_ar = sum(1 for b, _ in census.get("all-reduce", []) if b > 64)
    rec = {
        "config": config,
        "mode": mode,
        **({"bucket_bytes": bucket_bytes} if bucket_bytes is not None
           else {}),
        "step_ms": round(min(best, min(spreads)), 3),
        "step_ms_spread": [min(spreads), max(spreads)],
        "n_collectives": {k: len(v) for k, v in census.items()},
        "gradient_allreduce_instructions": n_grad_ar,
        "wire_bytes_per_step": int(census_wire_bytes(census, 8,
                                                     min_bytes=8)),
        **ov,
    }
    return rec


def main():
    rows = []
    for mode in ("allreduce", "reduce_scatter", "quantized"):
        rows.append(run_variant("wide_mlp", mode))
    ab = []
    for mode in ("reduce_scatter", "quantized"):
        for bucket in (4 << 20, 0):
            ab.append(run_variant("deep_narrow", mode, bucket_bytes=bucket))
    # the structural assertion the artifact carries: reduce-scatter mode
    # leaves NO gradient-sized all-reduce in the program
    assert all(r["gradient_allreduce_instructions"] == 0
               for r in rows if r["mode"] != "allreduce"), rows
    assert all(r["gradient_allreduce_instructions"] == 0 for r in ab), ab
    print(json.dumps({
        "probe": "comm/compute overlap census (ISSUE r8)",
        "mesh": "8 virtual CPU devices, single process",
        "iters_per_window": ITERS, "windows": WINDOWS,
        "method": "device-category trace spans; exposed = |comm-span "
                  "union minus compute-span union|, overlapped = "
                  "|intersection|, per step = /iters. Wire bytes from the "
                  "partitioned-HLO census under the ring model "
                  "(probe_common.collective_wire_bytes).",
        "mode_comparison_wide_mlp": rows,
        "bucketing_ab_deep_narrow": ab,
        "structural_assert":
            "no gradient all-reduce instruction in any "
            "reduce_scatter/quantized compiled step (checked above); "
            "the same contract is test-pinned in tests/test_zero_comm.py",
        "caveats": [
            "CPU-mesh: the 8 'devices' are host threads sharing this "
            "box's cores — collectives are memcpy+rendezvous, so the "
            "exposed/overlapped split reflects the host threadpool "
            "schedule, not an ICI/DMA engine; re-run on TPU hardware "
            "for the latency-hiding headline",
            "byte and instruction-count fields are exact properties of "
            "the compiled HLO and transfer to TPU unchanged",
        ],
    }, indent=1))


if __name__ == "__main__":
    main()
