"""Long-context scaling: flash-attention fwd+bwd across sequence lengths.

The framework's long-context story (SURVEY §5 row: LoD -> segment-ids +
true context parallelism) rests on the O(T)-memory Pallas kernel. This
prints the scaling curve — per-step time and achieved attention FLOP/s for
the kernel at T = 2k..64k, with the XLA composite alongside until it OOMs.

    env PYTHONPATH=/root/.axon_site:/root/repo \
        python tools/bench_longctx.py | tee BENCH_LONGCTX_r04.json
"""

from __future__ import annotations

import json
import time

import numpy as np


def _realize(x):
    return float(np.asarray(x).ravel()[0])


def _attn_flops(b, h, t, d):
    # qk + pv fwd, ~2.5x more for bwd (dq, dk, dv recompute): count fwd+bwd
    # as 3.5x fwd; the benchmark is CAUSAL, so only half the [T, T] score
    # matrix is live — standard flash-attention accounting halves the count
    return 3.5 * (2 * 2 * b * h * t * t * d) * 0.5


def _runner(T, backend, b=1, h=8, d=128, reps=3):
    """Compile a fwd+bwd runner; returns run() -> seconds/step or None on
    compile/OOM failure."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops import pallas_kernels as pk

    rng = np.random.RandomState(0)
    shape = (b, h, T, d)
    q = jnp.asarray(rng.randn(*shape).astype(np.float32),
                    dtype=jnp.bfloat16)
    k = jnp.asarray(rng.randn(*shape).astype(np.float32),
                    dtype=jnp.bfloat16)
    v = jnp.asarray(rng.randn(*shape).astype(np.float32),
                    dtype=jnp.bfloat16)

    def loss(q, k, v):
        if backend == "pallas":
            out = pk.flash_attention(q, k, v, causal=True)
        else:
            out = pk._attention_reference(q, k, v, 1.0 / d ** 0.5, True)
        return jnp.sum(out.astype(jnp.float32))

    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
    try:
        out = g(q, k, v)
        _realize(out[0][0, 0, 0, 0])
    except Exception as e:
        return None, f"failed: {type(e).__name__}"

    def run():
        t0 = time.time()
        for _ in range(reps):
            out = g(q, k, v)
        _realize(out[0][0, 0, 0, 0])
        return (time.time() - t0) / reps
    return run, None


def _ring_runner(T, b=1, h=8, d=128, reps=3):
    """Ring attention on a 1-device sp mesh: same math as the flash kernel
    plus the ring formulation around it (head-major transposes, the
    logsumexp merge, the custom-vjp plumbing). ring_ms/flash_ms - 1 is the
    committed 'ring formulation overhead' (VERDICT r4 #2)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.parallel.mesh import DeviceMesh
    from paddle_tpu.parallel.ring_attention import ring_attention_sharded

    rng = np.random.RandomState(0)
    mesh = DeviceMesh(jax.devices()[:1], {"sp": 1})
    shape = (b, T, h, d)                         # ring API is seq-major
    q, k, v = (jnp.asarray(rng.randn(*shape).astype(np.float32),
                           dtype=jnp.bfloat16) for _ in range(3))

    def loss(q, k, v):
        out = ring_attention_sharded(mesh, q, k, v, causal=True)
        return jnp.sum(out.astype(jnp.float32))

    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
    try:
        out = g(q, k, v)
        _realize(out[0][0, 0, 0, 0])
    except Exception as e:
        return None, f"failed: {type(e).__name__}"

    def run():
        t0 = time.time()
        for _ in range(reps):
            out = g(q, k, v)
        _realize(out[0][0, 0, 0, 0])
        return (time.time() - t0) / reps
    return run, None


def measure_pair(T, b=1, h=8, d=128, with_ring=False):
    """Interleaved flash/composite(/ring-of-1) rounds via the shared bench
    helper."""
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from bench import interleaved_best

    flash, ferr = _runner(T, "pallas", b, h, d)
    comp, cerr = _runner(T, "xla", b, h, d)
    ring, rerr = _ring_runner(T, b, h, d) if with_ring else (None, None)
    runners = {}
    if flash:
        runners["flash"] = flash
    if comp:
        runners["xla_composite"] = comp
    if ring:
        runners["ring_of_1"] = ring
    best = {"flash": None, "xla_composite": None, "ring_of_1": None}
    best.update(interleaved_best(runners) if runners else {})
    fl = _attn_flops(b, h, T, d)
    out = {}
    rows = [("flash", ferr), ("xla_composite", cerr)]
    if with_ring:
        rows.append(("ring_of_1", rerr))
    for name, err in rows:
        if best[name] is None:
            out[name] = {"status": err or "failed"}
        else:
            out[name] = {"status": "ok",
                         "ms": round(best[name] * 1e3, 2),
                         "attn_tflops": round(fl / best[name] / 1e12, 1)}
    if best.get("ring_of_1") and best.get("flash"):
        out["ring_formulation_overhead_pct"] = round(
            (best["ring_of_1"] / best["flash"] - 1.0) * 100, 1)
    return out


# v5e inter-chip interconnect: 1600 Gbit/s aggregate per chip (public
# spec sheet); a 1-D ring drives ONE neighbor link pair per rotation
# direction — assume 4 link pairs per chip, i.e. 400 Gbit/s = 50 GB/s
# effective per direction. The assumption is committed with the formula
# so hardware can falsify it.
_V5E_ICI_GBPS_PER_DIR = 50.0


def ring_predicted(flash_ms_by_T, sp_list=(2, 4, 8), b=1, h=8, d=128,
                   formulation_overhead_pct=3.3):
    """Analytic CP scaling line from MEASURED flash-block times (VERDICT
    r5 #8 — the honest extrapolation a single-chip environment supports).

    Formula (per ring step, sp shards, fwd+bwd totals):
      t_block(T, sp)  = t_flash(T) / sp^2          [score work is
            quadratic in the tile extents; causal skipping scales both
            sides of the ratio identically]
      bytes_rot(T,sp) = 6 * b*h*(T/sp)*d * 2B      [fwd rotates k+v (2
            tensors), bwd rotates k+v and the dk+dv partials (4), bf16]
      t_comm          = bytes_rot / ICI_BW_per_dir
      comm_over_compute = t_comm / t_block
      predicted_overhead_pct = max(0, comm_over_compute - 1) * 100
                               + measured ring-of-1 formulation overhead
            [rotation overlaps the NEXT block's compute — comm costs
            wall time only past ratio 1]
    """
    rows = []
    for T, flash_ms in sorted(flash_ms_by_T.items()):
        for sp in sp_list:
            t_block = flash_ms / (sp * sp)
            bytes_rot = 6 * b * h * (T // sp) * d * 2
            t_comm = bytes_rot / (_V5E_ICI_GBPS_PER_DIR * 1e9) * 1e3
            ratio = t_comm / t_block
            rows.append({
                "T": T, "sp": sp,
                "t_block_ms": round(t_block, 3),
                "rotated_MB_per_step": round(bytes_rot / 1e6, 2),
                "t_comm_ms": round(t_comm, 3),
                "comm_over_compute": round(ratio, 3),
                "predicted_overhead_pct": round(
                    max(0.0, ratio - 1.0) * 100
                    + formulation_overhead_pct, 1),
            })
    return {
        "ring_predicted": rows,
        "assumptions": {
            "ici_GBps_per_direction": _V5E_ICI_GBPS_PER_DIR,
            "measured_flash_fwd_bwd_ms": {str(t): v for t, v in
                                          sorted(flash_ms_by_T.items())},
            "formulation_overhead_pct_measured_ring_of_1":
                formulation_overhead_pct,
            "formula": "t_block=t_flash/sp^2; bytes=6*b*h*(T/sp)*d*2; "
                       "overhead=max(0, t_comm/t_block - 1) + measured "
                       "formulation overhead (comm overlaps compute)",
        },
    }


def main():
    import argparse
    import jax

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--predict_from", default=None,
                    help="path to a prior BENCH_LONGCTX artifact: emit "
                         "the analytic ring_predicted block from its "
                         "measured flash lanes (no hardware needed) and "
                         "exit")
    args = ap.parse_args()
    if args.predict_from:
        flash = {}
        with open(args.predict_from) as f:
            for line in f:
                rec = json.loads(line)
                if (isinstance(rec.get("flash"), dict)
                        and rec["flash"].get("status") == "ok"):
                    flash[int(rec["T"])] = rec["flash"]["ms"]
        sel = {t: flash[t] for t in (16384, 65536) if t in flash}
        print(json.dumps(ring_predicted(sel)), flush=True)
        return

    dev = jax.devices()[0]
    on_accel = dev.platform != "cpu"
    lengths = ((2048, 4096, 8192, 16384, 32768, 65536) if on_accel
               else (256,))
    flash_ms_by_T = {}
    for T in lengths:
        if on_accel:
            rec = {"T": T, **measure_pair(T, with_ring=T in (8192, 16384))}
            if rec.get("flash", {}).get("status") == "ok":
                flash_ms_by_T[T] = rec["flash"]["ms"]
        else:
            # CPU smoke: only the XLA composite runs (the Mosaic kernel
            # needs a TPU); label it as what it is
            run, err = _runner(T, "xla")
            rec = {"T": T,
                   "xla_composite_smoke": {"status": err or "ok"}}
            if run:
                run()
        print(json.dumps(rec), flush=True)
    sel = {t: flash_ms_by_T[t] for t in (16384, 65536)
           if t in flash_ms_by_T}
    if sel:
        print(json.dumps(ring_predicted(sel)), flush=True)
    print(json.dumps({
        "device_kind": getattr(dev, "device_kind", str(dev)),
        "note": "causal fwd+bwd, B=1 H=8 D=128 bf16; composite "
                "materializes [T,T] scores and is expected to OOM first",
    }), flush=True)


if __name__ == "__main__":
    main()
