"""Attribute the stacked-LSTM bench config's step time on the TPU.

The LSTM line's low MFU is structural, not a kernel defect: a recurrent
scan serializes T steps, and each tick's recurrent matmul on this config
is [B=64, H=256] x [256, 1024] — ~34 MFLOP, far too small to fill a
197-TFLOP/s MXU whose granularity wants >=10x that per dispatch. This
probe prints the numbers that show where the time actually goes: XLA's
own flops/bytes (roofline position), the scan tick count, and per-tick
wall time vs the per-tick ideal.

    env PYTHONPATH=/root/.axon_site:/root/repo python tools/probe_lstm.py
"""
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from probe_common import measure_step, roofline_fields  # noqa: E402


def main(b=64, t=64, emb=256, hid=256):
    import paddle_tpu as pt
    from paddle_tpu.models import stacked_lstm

    rng = np.random.RandomState(0)

    def build():
        loss, acc, _ = stacked_lstm.stacked_lstm_net(
            dict_dim=10000, emb_dim=emb, hid_dim=hid, max_len=t)
        return loss, pt.optimizer.AdamOptimizer(learning_rate=5e-4)

    def make_feed():
        return {"words": rng.randint(0, 10000, (b, t)).astype("int64"),
                "words@SEQLEN": np.full((b,), t, "int32"),
                "label": rng.randint(0, 2, (b, 1)).astype("int64")}

    m = measure_step(build, make_feed, iters=20)
    out = roofline_fields(m["step_s"], m["flops"], m["bytes_acc"])

    # 3 stacked LSTMs, each a T-tick scan, fwd + bwd (bwd re-scans) ->
    # sequential tick chain the step time divides over
    ticks = 3 * t * 2
    out.update({
        "sequential_ticks_fwd_bwd": ticks,
        "wall_us_per_tick": round(m["step_s"] / ticks * 1e6, 1),
        "recurrent_matmul_mflops_per_tick":
            round(2 * b * hid * (4 * hid) / 1e6, 1),
        "note": "a ~34-MFLOP matmul per tick cannot fill the MXU; the "
                "step is bound by the serialized scan ticks, not "
                "flops or HBM (both ideals are far below measured)",
    })
    print(json.dumps(out))


if __name__ == "__main__":
    main()
