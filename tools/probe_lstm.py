"""Attribute the stacked-LSTM bench config's step time on the TPU.

The LSTM line's low MFU is structural, not a kernel defect: a recurrent
scan serializes T steps, and each tick's recurrent matmul on this config
is [B=64, H=256] x [256, 1024] — ~34 MFLOP, far too small to fill a
197-TFLOP/s MXU whose granularity wants >=10x that per dispatch. This
probe prints the numbers that show where the time actually goes: XLA's
own flops/bytes (roofline position), the scan tick count, and per-tick
wall time vs the per-tick ideal.

    env PYTHONPATH=/root/.axon_site:/root/repo python tools/probe_lstm.py
"""
import json
import sys
import time

import numpy as np


def main(b=64, t=64, emb=256, hid=256):
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.models import stacked_lstm

    sys.path.insert(0, "/root/repo")

    pt.reset_default_programs()
    pt.reset_global_scope()
    rng = np.random.RandomState(0)
    with pt.core.unique_name.guard():
        loss, acc, _ = stacked_lstm.stacked_lstm_net(
            dict_dim=10000, emb_dim=emb, hid_dim=hid, max_len=t)
        opt = pt.optimizer.AdamOptimizer(learning_rate=5e-4)
        opt.minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    feed = {"words": jnp.asarray(rng.randint(0, 10000, (b, t))
                                 .astype("int64")),
            "words@SEQLEN": jnp.asarray(np.full((b,), t, "int32")),
            "label": jnp.asarray(rng.randint(0, 2, (b, 1)).astype("int64"))}
    prog, scope = pt.default_main_program(), pt.global_scope()
    compiled = exe._lookup_or_compile(prog, feed, [loss.name], scope)
    feed_vals = tuple(jnp.asarray(feed[n]) for n in compiled.feed_names)
    ro_vals = tuple(scope.get(n) for n in compiled.ro_names)
    rw_vals = tuple(scope.get(n) for n in compiled.rw_names)
    ex = compiled.fn.lower(feed_vals, ro_vals, rw_vals,
                           np.uint32(0)).compile()
    ca = ex.cost_analysis()
    ca = ca[0] if isinstance(ca, (list, tuple)) else ca
    bytes_acc = float(ca.get("bytes accessed", 0))
    flops = float(ca.get("flops", 0))

    o = exe.run(feed=feed, fetch_list=[loss], return_numpy=False)
    float(np.asarray(o[0]).ravel()[0])
    best = None
    for _ in range(3):
        t0 = time.time()
        fetched = []
        for _ in range(20):
            o = exe.run(feed=feed, fetch_list=[loss], return_numpy=False)
            fetched.append(o[0])
        float(np.asarray(fetched[-1]).ravel()[0])
        dt = (time.time() - t0) / 20
        best = dt if best is None else min(best, dt)

    # 3 stacked LSTMs, each a T-tick scan, fwd + bwd (bwd re-scans) ->
    # sequential tick chain the step time divides over
    ticks = 3 * t * 2
    per_tick_matmul_flops = 2 * b * hid * (4 * hid)
    print(json.dumps({
        "step_ms": round(best * 1e3, 2),
        "bytes_GB": round(bytes_acc / 1e9, 2),
        "flops_G": round(flops / 1e9, 1),
        "intensity_flops_per_byte": round(flops / bytes_acc, 1),
        "ideal_mxu_ms": round(flops / 197e12 * 1e3, 3),
        "ideal_hbm_ms": round(bytes_acc / 819e9 * 1e3, 3),
        "mfu": round(flops / best / 197e12, 4),
        "sequential_ticks_fwd_bwd": ticks,
        "wall_us_per_tick": round(best / ticks * 1e6, 1),
        "recurrent_matmul_mflops_per_tick":
            round(per_tick_matmul_flops / 1e6, 1),
        "note": "a ~34-MFLOP matmul per tick cannot fill the MXU; the "
                "step is bound by the serialized scan ticks, not "
                "flops or HBM (both ideals are far below measured)",
    }))


if __name__ == "__main__":
    main()
