"""Classic-CNN training throughput vs the reference's OWN published
baselines (reference benchmark/IntelOptimizedPaddle.md:29-65 — its best
in-repo training numbers): VGG-19 30.44 img/s and GoogLeNet 269.50 img/s,
both bs256 on a 2-socket Xeon 6148.

    env PYTHONPATH=/root/.axon_site:/root/repo \
        python tools/bench_classics.py | tee BENCH_CLASSICS_r03.json

Same audit fields + sync discipline as bench.py / bench_breadth.py.
"""

from __future__ import annotations

import json
import time

import numpy as np

_REFERENCE_BEST = {"vgg19": 30.44, "googlenet": 269.50}


def _measure_cnn(name, build_loss, batch, img_shape, iters=15):
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt

    pt.reset_default_programs()
    pt.reset_global_scope()
    rng = np.random.RandomState(0)
    with pt.core.unique_name.guard():
        loss = build_loss()
        pt.optimizer.MomentumOptimizer(learning_rate=3e-3,
                                       momentum=0.9).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    feed = {
        "img": jnp.asarray(rng.rand(*img_shape).astype("float32")),
        "label": jnp.asarray(rng.randint(0, 1000, (batch, 1))
                             .astype("int64")),
    }
    out = exe.run(feed=feed, fetch_list=[loss], return_numpy=False)
    float(np.asarray(out[0]).ravel()[0])

    # shared best-of-N discipline (bench._best_of); losses tracked across
    # ALL windows so the work-verification property holds
    import sys as _sys
    import os as _os
    _sys.path.insert(0, _os.path.dirname(_os.path.dirname(
        _os.path.abspath(__file__))))
    from bench import _best_of

    losses = []

    def window():
        fetched = []
        t0 = time.time()
        for _ in range(iters):
            out = exe.run(feed=feed, fetch_list=[loss], return_numpy=False)
            fetched.append(out[0])
        float(np.asarray(fetched[-1]).ravel()[0])
        w = time.time() - t0
        losses.extend(float(np.asarray(x).ravel()[0]) for x in fetched)
        return iters / w  # steps/sec; best window = least interference

    steps_per_sec = _best_of(3, window)
    dt = iters / steps_per_sec

    ca = exe.cost_analysis(feed=feed, fetch_list=[loss])
    flops = float(ca.get("flops", 0.0)) if ca else 0.0
    dev = jax.devices()[0]
    imgs_s = batch * iters / dt
    ref = _REFERENCE_BEST.get(name)
    rec = {
        "model": f"{name}_train_bs{batch}",
        "value": round(imgs_s, 2),
        "unit": "images/sec",
        "vs_reference_best": round(imgs_s / ref, 2) if ref else None,
        "evidence": {
            "device_kind": getattr(dev, "device_kind", str(dev)),
            "reference_best_images_per_sec": ref,
            "step_ms": round(dt / iters * 1e3, 2),
            "flops_per_step_xla": flops,
            "implied_tflops": round(flops * iters / dt / 1e12, 2),
            "loss_first": round(losses[0], 4),
            "loss_last": round(losses[-1], 4),
            "loss_decreased": bool(losses[-1] < losses[0]),
        },
    }
    print(json.dumps(rec), flush=True)
    return rec


def main():
    import jax
    from paddle_tpu import models
    on_accel = jax.devices()[0].platform != "cpu"
    batch = 128 if on_accel else 4
    iters = 15 if on_accel else 2

    def vgg():
        # vgg builds NCHW fp32 (the model's reference-mirroring layout)
        loss, acc, _ = models.vgg.vgg(depth=19, is_test=False)
        return loss

    def goog():
        loss, acc, _ = models.googlenet.googlenet_imagenet(
            is_test=False, data_format="NHWC", use_bf16=True)
        return loss

    recs = [_measure_cnn("vgg19", vgg, batch, (batch, 3, 224, 224), iters),
            _measure_cnn("googlenet", goog, batch, (batch, 224, 224, 3),
                         iters)]
    print(json.dumps({"all_losses_decreased":
                      all(r["evidence"]["loss_decreased"] for r in recs)}),
          flush=True)


if __name__ == "__main__":
    main()
