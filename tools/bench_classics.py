"""Classic-CNN train AND infer throughput vs the reference's OWN published
baselines (reference benchmark/IntelOptimizedPaddle.md — its best in-repo
numbers, 2-socket Xeon 6148 MKL-DNN): train bs256 VGG-19 30.44 / GoogLeNet
269.50 / AlexNet 626.53 img/s (:29-65), infer bs16 VGG-19 96.75 /
GoogLeNet 600.94 / AlexNet 850.51 img/s (:71-107).

    env PYTHONPATH=/root/.axon_site:/root/repo \
        python tools/bench_classics.py | tee BENCH_CLASSICS_r04.json

Same audit fields + sync discipline as bench.py / bench_breadth.py.
"""

from __future__ import annotations

import json
import time

import numpy as np

_REFERENCE_BEST = {"vgg19": 30.44, "googlenet": 269.50, "alexnet": 626.53}
_REFERENCE_BEST_INFER = {"vgg19": 96.75, "googlenet": 600.94,
                         "alexnet": 850.51}


def _measure_cnn(name, build_loss, batch, img_shape, iters=15):
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt

    pt.reset_default_programs()
    pt.reset_global_scope()
    rng = np.random.RandomState(0)
    with pt.core.unique_name.guard():
        loss = build_loss()
        pt.optimizer.MomentumOptimizer(learning_rate=3e-3,
                                       momentum=0.9).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    feed = {
        "img": jnp.asarray(rng.rand(*img_shape).astype("float32")),
        "label": jnp.asarray(rng.randint(0, 1000, (batch, 1))
                             .astype("int64")),
    }
    out = exe.run(feed=feed, fetch_list=[loss], return_numpy=False)
    float(np.asarray(out[0]).ravel()[0])

    # shared best-of-N discipline (bench._best_of); losses tracked across
    # ALL windows so the work-verification property holds
    import sys as _sys
    import os as _os
    _sys.path.insert(0, _os.path.dirname(_os.path.dirname(
        _os.path.abspath(__file__))))
    from bench import _best_of

    losses = []

    def window():
        fetched = []
        t0 = time.time()
        for _ in range(iters):
            out = exe.run(feed=feed, fetch_list=[loss], return_numpy=False)
            fetched.append(out[0])
        float(np.asarray(fetched[-1]).ravel()[0])
        w = time.time() - t0
        losses.extend(float(np.asarray(x).ravel()[0]) for x in fetched)
        return iters / w  # steps/sec; best window = least interference

    steps_per_sec = _best_of(3, window)
    dt = iters / steps_per_sec

    ca = exe.cost_analysis(feed=feed, fetch_list=[loss])
    flops = float(ca.get("flops", 0.0)) if ca else 0.0
    dev = jax.devices()[0]
    imgs_s = batch * iters / dt
    ref = _REFERENCE_BEST.get(name)
    rec = {
        "model": f"{name}_train_bs{batch}",
        "value": round(imgs_s, 2),
        "unit": "images/sec",
        "vs_reference_best": round(imgs_s / ref, 2) if ref else None,
        "evidence": {
            "device_kind": getattr(dev, "device_kind", str(dev)),
            "reference_best_images_per_sec": ref,
            "step_ms": round(dt / iters * 1e3, 2),
            "flops_per_step_xla": flops,
            "implied_tflops": round(flops * iters / dt / 1e12, 2),
            "loss_first": round(losses[0], 4),
            "loss_last": round(losses[-1], 4),
            "loss_decreased": bool(losses[-1] < losses[0]),
        },
    }
    print(json.dumps(rec), flush=True)
    return rec


def _measure_cnn_infer(name, build_logits, batch, img_shape, iters=30):
    """Inference img/s vs the reference's published bs16 infer table.

    Sync discipline mirrors bench._resnet_infer_throughput: step k's input
    derives (negligibly but really) from step k-1's output so the final
    realization bounds every timed dispatch through the tunnel."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt

    import os as _os
    import sys as _sys
    _sys.path.insert(0, _os.path.dirname(_os.path.dirname(
        _os.path.abspath(__file__))))
    from bench import _best_of

    pt.reset_default_programs()
    pt.reset_global_scope()
    with pt.core.unique_name.guard():
        logits = build_logits()
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.RandomState(3)
    img0 = jnp.asarray(rng.rand(*img_shape).astype("float32"))
    label = jnp.asarray(rng.randint(0, 1000, (batch, 1)).astype("int64"))
    out = exe.run(feed={"img": img0, "label": label}, fetch_list=[logits],
                  return_numpy=False)
    float(out[0][0, 0])

    def window():
        cur = img0
        t0 = time.time()
        out = None
        for _ in range(iters):
            out = exe.run(feed={"img": cur, "label": label},
                          fetch_list=[logits], return_numpy=False)
            cur = img0 + out[0][0, 0].astype(jnp.float32) * 1e-30
        float(out[0][0, 0])
        return batch * iters / (time.time() - t0)

    imgs_s = _best_of(3, window)
    dev = jax.devices()[0]
    ref = _REFERENCE_BEST_INFER.get(name)
    rec = {
        "model": f"{name}_infer_bs{batch}",
        "value": round(imgs_s, 2),
        "unit": "images/sec",
        "vs_reference_best": round(imgs_s / ref, 2) if ref else None,
        "evidence": {
            "device_kind": getattr(dev, "device_kind", str(dev)),
            "reference_best_images_per_sec": ref,
            "step_ms": round(batch / imgs_s * 1e3, 2),
        },
    }
    print(json.dumps(rec), flush=True)
    return rec


def main():
    import jax
    from paddle_tpu import models
    on_accel = jax.devices()[0].platform != "cpu"
    batch = 128 if on_accel else 4
    iters = 15 if on_accel else 2
    infer_bs = 16 if on_accel else 4
    infer_iters = 30 if on_accel else 2

    def vgg():
        # vgg builds NCHW fp32 (the model's reference-mirroring layout)
        loss, acc, _ = models.vgg.vgg(depth=19, is_test=False)
        return loss

    def goog():
        loss, acc, _ = models.googlenet.googlenet_imagenet(
            is_test=False, data_format="NHWC", use_bf16=True)
        return loss

    def alex():
        loss, acc, _ = models.alexnet.alexnet_imagenet(
            is_test=False, data_format="NHWC", use_bf16=True)
        return loss

    recs = [_measure_cnn("vgg19", vgg, batch, (batch, 3, 224, 224), iters),
            _measure_cnn("googlenet", goog, batch, (batch, 224, 224, 3),
                         iters),
            _measure_cnn("alexnet", alex, batch, (batch, 224, 224, 3),
                         iters)]
    print(json.dumps({"all_losses_decreased":
                      all(r["evidence"]["loss_decreased"] for r in recs)}),
          flush=True)

    def vgg_i():
        _, _, logits = models.vgg.vgg(depth=19, is_test=True)
        return logits

    def goog_i():
        _, _, logits = models.googlenet.googlenet_imagenet(
            is_test=True, data_format="NHWC", use_bf16=True)
        return logits

    def alex_i():
        _, _, logits = models.alexnet.alexnet_imagenet(
            is_test=True, data_format="NHWC", use_bf16=True)
        return logits

    _measure_cnn_infer("vgg19", vgg_i, infer_bs,
                       (infer_bs, 3, 224, 224), infer_iters)
    _measure_cnn_infer("googlenet", goog_i, infer_bs,
                       (infer_bs, 224, 224, 3), infer_iters)
    _measure_cnn_infer("alexnet", alex_i, infer_bs,
                       (infer_bs, 224, 224, 3), infer_iters)


if __name__ == "__main__":
    main()
