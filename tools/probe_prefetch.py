"""Probe: prefetcher link utilization vs the CONCURRENTLY-measured link.

Measures (1) raw uint8 h2d staging bandwidth several times, (2) the
DevicePrefetcher-fed ResNet bs128 train loop, (3) bandwidth again — so the
fed rate can be judged against the link speed of the SAME session (the dev
tunnel drifts ~2x between sessions; VERDICT r3 weak #1 was exactly a fed
number divided by another window's link measure).

    env PYTHONPATH=/root/.axon_site:/root/repo python tools/probe_prefetch.py
"""
import json
import sys
import time

import numpy as np


def link_mbps(batch=128, reps=3):
    import jax

    x = (np.random.RandomState(0).rand(batch, 224, 224, 3) * 255
         ).astype("uint8")
    d = jax.device_put(x)
    _ = np.asarray(d[0, 0, 0, 0])
    best = None
    for _ in range(reps):
        t0 = time.time()
        d = jax.device_put(x)
        _ = np.asarray(d[0, 0, 0, 0])
        dt = time.time() - t0
        best = dt if best is None else min(best, dt)
    return x.nbytes / best / 1e6


def main(batch=128, iters=16):
    import jax.numpy as jnp

    sys.path.insert(0, "/root/repo")
    import bench

    link_before = link_mbps(batch)

    exe, loss = bench._build_resnet_train(batch)
    # warm the compiled step with a staged batch
    rng = np.random.RandomState(0)
    feed0 = {
        "img": jnp.asarray((rng.rand(batch, 224, 224, 3) * 255)
                           .astype("uint8")),
        "label": jnp.asarray(rng.randint(0, 1000, (batch, 1))
                             .astype("int64")),
    }
    out = exe.run(feed=feed0, fetch_list=[loss], return_numpy=False)
    float(out[0])

    from paddle_tpu.data.feeder import staging_specs
    from paddle_tpu.data.prefetch import DevicePrefetcher

    host_batches = [
        {"img": rng.rand(batch, 224, 224, 3).astype("float32"),
         "label": rng.randint(0, 1000, (batch, 1)).astype("int64")}
        for _ in range(4)
    ]
    specs = staging_specs()

    results = {}
    for cap in (2, 4):
        def feed_iter():
            for i in range(iters + 2):
                yield host_batches[i % len(host_batches)]

        pf = iter(DevicePrefetcher(feed_iter, capacity=cap, staging=specs))
        for _ in range(2):
            out = exe.run(feed=next(pf), fetch_list=[loss],
                          return_numpy=False)
        float(out[0])
        fetched = []
        t0 = time.time()
        for feed in pf:
            out = exe.run(feed=feed, fetch_list=[loss], return_numpy=False)
            fetched.append(out[0])
        float(fetched[-1])
        dt = time.time() - t0
        rate = batch * len(fetched) / dt
        results[f"cap{cap}_imgs_s"] = round(rate, 2)
        results[f"cap{cap}_wire_MBps"] = round(
            rate * 224 * 224 * 3 / 1e6, 2)

    link_after = link_mbps(batch)
    results["link_before_MBps"] = round(link_before, 1)
    results["link_after_MBps"] = round(link_after, 1)
    link = max(link_before, link_after)
    results["utilization_cap2"] = round(
        results["cap2_wire_MBps"] / link, 3)
    results["utilization_cap4"] = round(
        results["cap4_wire_MBps"] / link, 3)
    print(json.dumps(results))


if __name__ == "__main__":
    main()
