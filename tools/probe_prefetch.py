"""Probe: prefetcher link utilization, stream scaling, and drain ceilings.

ONE flag-driven probe (the r12 numbered-copy consolidation pattern;
probe_prefetch2.py folded in here). `--exp` selects the methodology,
names preserving the lineage:

  utilization   (the original probe_prefetch, r4): raw uint8 h2d staging
                bandwidth measured BEFORE AND AFTER the DevicePrefetcher-
                fed ResNet bs128 train loop, so the fed rate is judged
                against the link speed of the SAME session (the dev
                tunnel drifts ~2x between sessions; VERDICT r3 weak #1
                was exactly a fed number divided by another window's
                link measure).
  streams       (probe_prefetch2 part 1, r4 follow-up): raw uint8 link
                at 1/2/3 concurrent put streams + the float->uint8
                conversion cost on the staging thread.
  drain         (probe_prefetch2 part 2): drain-only DevicePrefetcher
                rates (no training step) at several (stage_threads,
                capacity) settings — the pipeline's own ceiling.

    env PYTHONPATH=/root/.axon_site:/root/repo \\
        python tools/probe_prefetch.py --exp utilization
"""
import argparse
import json
import sys
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np


def link_mbps(batch=128, reps=3):
    import jax

    x = (np.random.RandomState(0).rand(batch, 224, 224, 3) * 255
         ).astype("uint8")
    d = jax.device_put(x)
    _ = np.asarray(d[0, 0, 0, 0])
    best = None
    for _ in range(reps):
        t0 = time.time()
        d = jax.device_put(x)
        _ = np.asarray(d[0, 0, 0, 0])
        dt = time.time() - t0
        best = dt if best is None else min(best, dt)
    return x.nbytes / best / 1e6


def exp_utilization(batch=128, iters=16):
    """Fed-rate vs same-session link: the original probe_prefetch."""
    import jax.numpy as jnp

    sys.path.insert(0, "/root/repo")
    import bench

    link_before = link_mbps(batch)

    exe, loss = bench._build_resnet_train(batch)
    # warm the compiled step with a staged batch
    rng = np.random.RandomState(0)
    feed0 = {
        "img": jnp.asarray((rng.rand(batch, 224, 224, 3) * 255)
                           .astype("uint8")),
        "label": jnp.asarray(rng.randint(0, 1000, (batch, 1))
                             .astype("int64")),
    }
    out = exe.run(feed=feed0, fetch_list=[loss], return_numpy=False)
    float(out[0])

    from paddle_tpu.data.feeder import staging_specs
    from paddle_tpu.data.prefetch import DevicePrefetcher

    host_batches = [
        {"img": rng.rand(batch, 224, 224, 3).astype("float32"),
         "label": rng.randint(0, 1000, (batch, 1)).astype("int64")}
        for _ in range(4)
    ]
    specs = staging_specs()

    results = {}
    for cap in (2, 4):
        def feed_iter():
            for i in range(iters + 2):
                yield host_batches[i % len(host_batches)]

        pf = iter(DevicePrefetcher(feed_iter, capacity=cap, staging=specs))
        for _ in range(2):
            out = exe.run(feed=next(pf), fetch_list=[loss],
                          return_numpy=False)
        float(out[0])
        fetched = []
        t0 = time.time()
        for feed in pf:
            out = exe.run(feed=feed, fetch_list=[loss], return_numpy=False)
            fetched.append(out[0])
        float(fetched[-1])
        dt = time.time() - t0
        rate = batch * len(fetched) / dt
        results[f"cap{cap}_imgs_s"] = round(rate, 2)
        results[f"cap{cap}_wire_MBps"] = round(
            rate * 224 * 224 * 3 / 1e6, 2)

    link_after = link_mbps(batch)
    results["link_before_MBps"] = round(link_before, 1)
    results["link_after_MBps"] = round(link_after, 1)
    link = max(link_before, link_after)
    results["utilization_cap2"] = round(
        results["cap2_wire_MBps"] / link, 3)
    results["utilization_cap4"] = round(
        results["cap4_wire_MBps"] / link, 3)
    return results


def exp_streams(batch=128):
    """Concurrent-stream link scaling + staging conversion cost (the
    first half of the former probe_prefetch2)."""
    import jax

    img_u8 = (np.random.RandomState(0).rand(batch, 224, 224, 3) * 255
              ).astype("uint8")
    nbytes = img_u8.nbytes

    d = jax.device_put(img_u8)
    _ = np.asarray(d[0, 0, 0, 0])

    out = {}

    def put_one(x):
        h = jax.device_put(x)
        _ = np.asarray(h[0, 0, 0, 0])
        return h

    for streams in (1, 2, 3):
        pool = ThreadPoolExecutor(max_workers=streams)
        reps = 6
        best = None
        for _ in range(2):
            t0 = time.time()
            futs = [pool.submit(put_one, img_u8) for _ in range(reps)]
            for f in futs:
                f.result()
            dt = time.time() - t0
            best = dt if best is None else min(best, dt)
        out[f"link_MBps_{streams}stream"] = round(
            nbytes * reps / best / 1e6, 2)
        pool.shutdown()

    # conversion cost on the staging thread (fp32 batch -> uint8 wire)
    img_f32 = np.random.RandomState(1).rand(batch, 224, 224, 3).astype(
        "float32")
    t0 = time.time()
    for _ in range(5):
        w = (img_f32 * 255.0).astype("uint8")  # noqa: F841
    out["convert_ms_per_batch"] = round((time.time() - t0) / 5 * 1e3, 1)
    return out


def exp_drain(batch=128):
    """Drain-only prefetcher ceilings (the second half of the former
    probe_prefetch2): no training step, just the pipeline."""
    import paddle_tpu as pt  # noqa: F401  (registers staging helpers)
    from paddle_tpu.data.prefetch import DevicePrefetcher

    out = {}
    host_batches = [
        {"img": np.random.RandomState(i).rand(batch, 224, 224, 3)
         .astype("float32"),
         "label": np.random.RandomState(i).randint(0, 1000, (batch, 1))
         .astype("int64")}
        for i in range(4)
    ]
    specs = {"img": ("uint8", 1.0 / 255.0)}

    def feed_iter():
        for i in range(12):
            yield host_batches[i % 4]

    for threads, cap in ((1, 4), (2, 4), (3, 6), (4, 8)):
        best = None
        for _ in range(2):
            pf = iter(DevicePrefetcher(feed_iter, capacity=cap,
                                       staging=specs,
                                       stage_threads=threads))
            first = next(pf)  # warm
            _ = np.asarray(first["img"][0, 0, 0, 0])
            t0 = time.time()
            n = 0
            last = None
            for b in pf:
                last = b
                n += 1
            _ = np.asarray(last["img"][0, 0, 0, 0])
            dt = time.time() - t0
            rate = n * batch / dt
            best = rate if best is None else max(best, rate)
        out[f"drain_imgs_per_s_t{threads}_c{cap}"] = round(best, 2)
        out[f"drain_wire_MBps_t{threads}_c{cap}"] = round(
            best * 224 * 224 * 3 / 1e6, 2)
    return out


EXPERIMENTS = {"utilization": exp_utilization, "streams": exp_streams,
               "drain": exp_drain}


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--exp", choices=sorted(EXPERIMENTS),
                   default="utilization")
    p.add_argument("--batch", type=int, default=128)
    p.add_argument("--iters", type=int, default=16,
                   help="utilization: fed train steps per capacity")
    args = p.parse_args()
    if args.exp == "utilization":
        results = exp_utilization(args.batch, args.iters)
    else:
        results = EXPERIMENTS[args.exp](args.batch)
    print(json.dumps(results, indent=1))


if __name__ == "__main__":
    main()
