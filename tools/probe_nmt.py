"""Attribute the transformer-NMT bench config's step time on the TPU.

Same roofline-position analysis probe_lm.py gives the LM line: XLA's own
bytes-accessed + flops for the compiled train step, so the measured MFU can
be read against the chip's 240 flops/byte balance point instead of standing
as a bare number.

    env PYTHONPATH=/root/.axon_site:/root/repo python tools/probe_nmt.py
"""
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from probe_common import (V5E_HBM_BPS, V5E_PEAK_TFLOPS,  # noqa: E402
                          measure_step, roofline_fields)


def main(b=16, t=256):
    import paddle_tpu as pt
    from paddle_tpu.models import transformer

    rng = np.random.RandomState(0)

    def build():
        loss, _ = transformer.transformer(
            src_vocab=16000, tgt_vocab=16000, max_len=t, d_model=512,
            d_inner=2048, num_heads=8, num_layers=4, dropout=0.0)
        return loss, pt.optimizer.AdamOptimizer(learning_rate=1e-4)

    def make_feed():
        return {"src": rng.randint(1, 16000, (b, t)).astype("int64"),
                "src@SEQLEN": np.full((b,), t, "int32"),
                "tgt": rng.randint(1, 16000, (b, t)).astype("int64"),
                "tgt@SEQLEN": np.full((b,), t, "int32"),
                "lbl": rng.randint(1, 16000, (b, t)).astype("int64")}

    m = measure_step(build, make_feed, iters=15,
                     hlo_path="/tmp/nmt_train.hlo")
    out = roofline_fields(m["step_s"], m["flops"], m["bytes_acc"])
    if m["flops"] and m["bytes_acc"]:
        out["roofline_mfu_cap"] = round(
            m["flops"] / max(m["flops"] / V5E_PEAK_TFLOPS,
                             m["bytes_acc"] / V5E_HBM_BPS)
            / V5E_PEAK_TFLOPS, 3)
    out["tokens_per_s"] = round(b * t / m["step_s"])
    print(json.dumps(out))


if __name__ == "__main__":
    main()
