"""Attribute the transformer-NMT bench config's step time on the TPU.

Same roofline-position analysis probe_lm.py gives the LM line: XLA's own
bytes-accessed + flops for the compiled train step, so the measured MFU can
be read against the chip's 240 flops/byte balance point instead of standing
as a bare number.

    env PYTHONPATH=/root/.axon_site:/root/repo python tools/probe_nmt.py
"""
import json
import sys
import time

import numpy as np


def main(b=16, t=256):
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.models import transformer

    sys.path.insert(0, "/root/repo")

    pt.reset_default_programs()
    pt.reset_global_scope()
    rng = np.random.RandomState(0)
    with pt.core.unique_name.guard():
        loss, _ = transformer.transformer(
            src_vocab=16000, tgt_vocab=16000, max_len=t, d_model=512,
            d_inner=2048, num_heads=8, num_layers=4, dropout=0.0)
        opt = pt.optimizer.AdamOptimizer(learning_rate=1e-4)
        opt.minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    feed = {"src": jnp.asarray(rng.randint(1, 16000, (b, t)).astype("int64")),
            "src@SEQLEN": jnp.asarray(np.full((b,), t, "int32")),
            "tgt": jnp.asarray(rng.randint(1, 16000, (b, t)).astype("int64")),
            "tgt@SEQLEN": jnp.asarray(np.full((b,), t, "int32")),
            "lbl": jnp.asarray(rng.randint(1, 16000, (b, t)).astype("int64"))}
    prog, scope = pt.default_main_program(), pt.global_scope()
    compiled = exe._lookup_or_compile(prog, feed, [loss.name], scope)
    feed_vals = tuple(jnp.asarray(feed[n]) for n in compiled.feed_names)
    ro_vals = tuple(scope.get(n) for n in compiled.ro_names)
    rw_vals = tuple(scope.get(n) for n in compiled.rw_names)
    ex = compiled.fn.lower(feed_vals, ro_vals, rw_vals,
                           np.uint32(0)).compile()
    with open("/tmp/nmt_train.hlo", "w") as f:
        f.write(ex.as_text())
    ca = ex.cost_analysis()
    ca = ca[0] if isinstance(ca, (list, tuple)) else ca
    bytes_acc = float(ca.get("bytes accessed", 0))
    flops = float(ca.get("flops", 0))

    o = exe.run(feed=feed, fetch_list=[loss], return_numpy=False)
    float(np.asarray(o[0]).ravel()[0])
    best = None
    for _ in range(3):
        t0 = time.time()
        fetched = []
        for _ in range(15):
            o = exe.run(feed=feed, fetch_list=[loss], return_numpy=False)
            fetched.append(o[0])
        float(np.asarray(fetched[-1]).ravel()[0])
        dt = (time.time() - t0) / 15
        best = dt if best is None else min(best, dt)
    print(json.dumps({
        "step_ms": round(best * 1e3, 2),
        "bytes_GB": round(bytes_acc / 1e9, 2),
        "flops_G": round(flops / 1e9, 1),
        "intensity_flops_per_byte": round(flops / bytes_acc, 1),
        "ideal_mxu_ms": round(flops / 197e12 * 1e3, 2),
        "ideal_hbm_ms": round(bytes_acc / 819e9 * 1e3, 2),
        "roofline_mfu_cap": round(
            flops / max(flops / 197e12, bytes_acc / 819e9) / 197e12, 3),
        "mfu": round(flops / best / 197e12, 4),
        "tokens_per_s": round(b * t / best),
    }))


if __name__ == "__main__":
    main()
