#!/usr/bin/env python
"""Per-request latency decomposition bench: the BENCH_REQTRACE artifact.

Drives a ContinuousBatchingEngine through the EngineServer RPC with more
in-flight requests than slots (so queue-wait is real), then reads the
engine's completed_log and checks the acceptance bar for the r16
observability tentpole: for EVERY request,

    queue_wait + prefill + decode + transport  ==  end-to-end latency

within 5% (the phases partition [submit, frame-sent] by construction —
the band is float/callback-ordering headroom, not slack in the
definition). Also scrapes /metrics once and asserts the labeled
histogram family is present for all four phases.

With --speculative the engine decodes speculatively and the check
extends to the r22 SUB-phases: `phases(subphases=True)` additionally
reports spec_draft/spec_verify, which are parts OF the prefill+decode
window, so the 4-phase partition must STILL sum to e2e and the
sub-phase pair must fit inside prefill+decode (same band).

    python tools/bench_reqtrace.py --out BENCH_REQTRACE_r16.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def run(n_requests: int = 12, n_slots: int = 2, max_new: int = 6,
        band: float = 0.05, speculative: bool = False) -> dict:
    from paddle_tpu.serving_engine import (ContinuousBatchingEngine,
                                           EngineClient, EngineServer,
                                           scrape_healthz, scrape_metrics)
    spec = None
    if speculative:
        from paddle_tpu.serving import SpecConfig
        spec = SpecConfig(gamma=2, draft="int8")
    eng = ContinuousBatchingEngine(n_slots=n_slots, vocab=100, max_len=16,
                                   d_model=32, d_inner=64, num_heads=4,
                                   num_layers=2, speculative=spec)
    with EngineServer(eng) as srv:
        host, port = srv.address
        with EngineClient(host, port) as c:
            for i in range(n_requests):
                # varied prompt lengths: prefill spans several ticks
                c.send_gen([3] * (1 + i % 4), max_new=max_new,
                           request_id=f"bench-{i}")
            for _ in range(n_requests):
                c.recv_done()
        deadline = time.time() + 10
        while time.time() < deadline and any(
                r.sent_pc is None for r in eng.completed_log):
            time.sleep(0.02)   # let the writer's on_sent callbacks land
        metrics_text = scrape_metrics(*srv.metrics_address)
        health = scrape_healthz(*srv.metrics_address)

    rows, worst = [], 0.0
    for req in eng.completed_log:
        ph = req.phases()
        e2e = req.e2e_s()
        ssum = sum(ph.values())
        err = abs(ssum - e2e) / e2e if e2e > 0 else 0.0
        worst = max(worst, err)
        row = {
            "request_id": req.request_id,
            "prompt_len": len(req.prompt),
            "new_tokens": len(req.tokens),
            "phases_ms": {k: round(v * 1e3, 4) for k, v in ph.items()},
            "sum_ms": round(ssum * 1e3, 4),
            "e2e_ms": round(e2e * 1e3, 4),
            "rel_err": round(err, 6),
            "conservation_ok": err <= band,
        }
        if speculative:
            # sub-phase containment: spec_draft+spec_verify are parts
            # of the prefill+decode window, never a fifth partition
            # member — the 4-phase sum above must be untouched by them
            sub = req.phases(subphases=True)
            spec_s = sub["spec_draft"] + sub["spec_verify"]
            window = ph["prefill"] + ph["decode"]
            row["subphases_ms"] = {
                "spec_draft": round(sub["spec_draft"] * 1e3, 4),
                "spec_verify": round(sub["spec_verify"] * 1e3, 4)}
            row["subphase_ok"] = spec_s <= window * (1 + band)
        rows.append(row)
    assert len(rows) == n_requests, (len(rows), n_requests)
    assert all(r["conservation_ok"] for r in rows), \
        [r for r in rows if not r["conservation_ok"]]
    if speculative:
        assert all(r["subphase_ok"] for r in rows), \
            [r for r in rows if not r["subphase_ok"]]

    series_ok = {
        phase: (f'phase="{phase}"' in metrics_text)
        for phase in ("queue_wait", "prefill", "decode", "transport")}
    series_ok["e2e"] = "ptpu_request_e2e_seconds_count" in metrics_text
    assert all(series_ok.values()), series_ok

    # with n_requests > n_slots some requests MUST have queued: the
    # decomposition is measuring something real, not all-zeros
    queued = [r for r in rows if r["phases_ms"]["queue_wait"] > 1.0]
    return {
        "bench": "reqtrace",
        "config": {"n_requests": n_requests, "n_slots": n_slots,
                   "max_new": max_new, "band": band},
        "summary": {
            "worst_rel_err": round(worst, 6),
            "n_queued": len(queued),
            "metrics_series_present": series_ok,
            "healthz_status": health.get("status"),
            "conservation_ok": worst <= band,
        },
        "rows": rows,
        "note": ("CPU-mesh measurement; the conservation property "
                 "(phases partition [submit, frame-sent]) is "
                 "clock-structural and transfers to TPU unchanged."),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    ap.add_argument("--requests", type=int, default=12)
    ap.add_argument("--slots", type=int, default=2)
    ap.add_argument("--speculative", action="store_true",
                    help="decode speculatively; also check the "
                         "spec_draft/spec_verify sub-phase containment")
    args = ap.parse_args()
    doc = run(n_requests=args.requests, n_slots=args.slots,
              speculative=args.speculative)
    doc["ts"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    out = json.dumps(doc, indent=1)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out + "\n")
        print(f"wrote {args.out}: worst_rel_err="
              f"{doc['summary']['worst_rel_err']}, "
              f"n_queued={doc['summary']['n_queued']}")
    else:
        print(out)


if __name__ == "__main__":
    main()
