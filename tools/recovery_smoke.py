#!/usr/bin/env python
"""Kill-the-process-mid-run recovery smoke (ROADMAP item 5 acceptance bar).

Orchestrates REAL process deaths through the elastic fault-injection hook
(PTPU_FAULT_INJECT, paddle_tpu/parallel/elastic.py) and asserts recovery:

  phase A  supervised preemption: a child training dp=2 SIGKILLs itself
           mid-run on its first attempt; trainer.Supervisor relaunches
           it; the resumed run restores the latest committed snapshot
           and its per-step fixed-seed losses match the uninterrupted
           reference run EXACTLY (bitwise — the snapshot carries the RNG
           run counter).
  phase B  dp-world resize: crash a dp=2 run, restart it with dp=4; the
           resumed losses match the reference within ATOL_RESIZE (fp32
           collectives regroup the mean across a different shard count —
           reduction-order ulps, the r09/r11 parity regime).
  phase C  crash DURING a snapshot write (SIGKILL at a byte offset of
           the staged payload): the surviving directory is uncommitted,
           restore falls back to the previous committed snapshot, and
           the relaunched run still reproduces the reference exactly.

`--world 4` runs the MULTI-RANK phases instead (the chief-commits
barrier over a simulated 4-rank ProcessWorld, parallel/process_world.py):

  phase D  SIGKILL a NON-CHIEF rank mid-barrier (crash_rank:2@ack at the
           first snapshot attempt): the whole gang dies with nothing
           committed, the restart re-trains and commits through the full
           barrier, losses match the uninterrupted dp4 reference
           BITWISE.
  phase E  SIGKILL the CHIEF mid-COMMIT (crash_rank:0@commit: after the
           directory rename, before the COMMIT marker): the restart
           finds only an UNCOMMITTED snapshot dir, starts clean, and
           still reproduces the reference exactly; the uncommitted
           leftover stays on disk for the run_ci.sh lint negative check
           (lint_program --restore_dir must exit 1 on it).

Child modes (also used by tests/test_elastic.py /
tests/test_process_world.py):
  --child          one training run: restore-if-possible, train to
                   --steps, snapshot every --snap_every (through the
                   barrier when --world > 1), append per-step losses to
                   --out as JSON lines; --fault_once arms
                   PTPU_FAULT_INJECT for exactly ONE attempt (a sentinel
                   file marks the armed attempt)
  --atomic-child   no-mesh snapshot writer for the crash-mid-save
                   atomicity property test: commit generation 0, then
                   save generation 1 (which PTPU_FAULT_INJECT may kill
                   at any byte offset)
  --world-atomic-child
                   mesh-backed MULTI-RANK writer for the crash-anywhere
                   property test: dp4 sharded + replicated state over a
                   4-rank world; commit generation 0 through the
                   barrier, then save generation 1 under the fault
                   (crash_rank:<r>@<phase>[@<offset>])

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python tools/recovery_smoke.py
    ... python tools/recovery_smoke.py --world 4
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

ATOL_RESIZE = 1e-5
STEPS = 8
SNAP_EVERY = 2
CRASH_STEP = 5


# ---------------------------------------------------------------------------
# child: one (resumable) training run
# ---------------------------------------------------------------------------

def _build_model():
    """EXACTLY tools/lint_program.py's `--model mnist --optimizer
    momentum` program, so the CI stanza can lint the restored program's
    sharded-state placement against the snapshots this child commits."""
    import paddle_tpu as pt
    from paddle_tpu import models
    loss = models.mnist.mlp()[0]
    pt.optimizer.MomentumOptimizer(0.1, momentum=0.9).minimize(loss)
    return loss


def _feed_for_step(i):
    import numpy as np
    rng = np.random.RandomState(1000 + i)
    return {"img": rng.rand(8, 784).astype("float32"),
            "label": rng.randint(0, 10, (8, 1)).astype("int64")}


def run_child(args) -> int:
    import jax

    import paddle_tpu as pt
    from paddle_tpu.parallel import ParallelExecutor, elastic
    from paddle_tpu.parallel.mesh import DeviceMesh
    from paddle_tpu.parallel.strategy import BuildStrategy, ReduceStrategy

    fresh = elastic.latest_snapshot(args.root) is None
    if args.fault_if_fresh and fresh:
        # self-arming fault: only the FIRST attempt crashes, so one
        # Supervisor argv covers crash and recovery
        os.environ["PTPU_FAULT_INJECT"] = args.fault_if_fresh
    if args.fault_once:
        # arm for exactly ONE attempt, committed-or-not (a barrier kill
        # commits nothing, so "fresh" would re-arm forever): a sentinel
        # file marks that some attempt already ran armed
        sentinel = os.path.join(args.root, ".fault_armed")
        os.makedirs(args.root, exist_ok=True)
        if not os.path.exists(sentinel):
            with open(sentinel, "w") as f:
                f.write(args.fault_once)
            os.environ["PTPU_FAULT_INJECT"] = args.fault_once

    with pt.core.unique_name.guard():
        loss = _build_model()
    bst = BuildStrategy()
    bst.reduce_strategy = ReduceStrategy.ReduceScatter
    mesh = DeviceMesh(jax.devices()[:args.dp], {"dp": args.dp})
    pexe = ParallelExecutor(loss_name=loss.name, build_strategy=bst,
                            mesh=mesh)
    pt.Executor().run(pt.default_startup_program())
    world = None
    if args.world > 1:
        from paddle_tpu.parallel.process_world import ProcessWorld
        world = ProcessWorld(args.world)
    start = 0
    if not fresh:
        meta = elastic.restore_train_state(args.root, executor=pexe)
        start = int(meta["step"])
    with open(args.out, "a") as f:
        for i in range(start, args.steps):
            elastic.maybe_crash_at_step(i)
            val = float(pexe.run(feed=_feed_for_step(i),
                                 fetch_list=[loss])[0])
            f.write(json.dumps({"step": i, "loss": val}) + "\n")
            f.flush()
            if (i + 1) % args.snap_every == 0:
                path = elastic.save_train_state(args.root, executor=pexe,
                                                step=i + 1, world=world,
                                                barrier_deadline_s=30)
                if world is not None and path is None:
                    print(f"snapshot at step {i + 1} aborted at the "
                          f"barrier; continuing", file=sys.stderr)
    return 0


# ---------------------------------------------------------------------------
# child: mesh-free snapshot writer (atomicity property test)
# ---------------------------------------------------------------------------

def run_atomic_child(args) -> int:
    import numpy as np

    from paddle_tpu.parallel import elastic

    # shapes/seed mirror tests/test_elastic.py _host_snapshot_args: the
    # parent checks surviving state against this exact generation 0
    rng = np.random.RandomState(7)
    arrays0 = {f"w_{k}": rng.randn(16, 4).astype("f4") for k in range(3)}
    arrays1 = {k: v + 1.0 for k, v in arrays0.items()}

    from paddle_tpu.framework.program import Program, program_guard
    from paddle_tpu.framework.scope import Scope

    def _save(arrays, step, fault_env=None):
        prog, startup = Program(), Program()
        scope = Scope()
        with program_guard(prog, startup):
            for name, val in arrays.items():
                prog.global_block().create_var(
                    name=name, shape=list(val.shape), dtype="float32",
                    persistable=True)
                scope.set_var(name, val)
        if fault_env is not None:
            os.environ["PTPU_FAULT_INJECT"] = fault_env
        elastic.save_train_state(args.root, program=prog, scope=scope,
                                 step=step)

    _save(arrays0, step=0)                       # generation 0: committed
    _save(arrays1, step=1, fault_env=args.fault or "")  # gen 1: may die
    return 0


# ---------------------------------------------------------------------------
# child: multi-rank barrier writer (crash-anywhere property test)
# ---------------------------------------------------------------------------

def world_atomic_arrays(generation: int):
    """The deterministic state both sides of the property test agree on:
    one dp-sharded [8, 6] matrix (its rows spread across every rank's
    devices, so EVERY rank stages real payload) plus one replicated
    [4, 4] matrix (written once, by whichever rank owns its replica-0
    device). Generation g adds g to every element."""
    import numpy as np
    rng = np.random.RandomState(11)
    return {"sharded_w": rng.randn(8, 6).astype("f4") + generation,
            "repl_w": rng.randn(4, 4).astype("f4") + generation}


def run_world_atomic_child(args) -> int:
    import jax
    import numpy as np

    from paddle_tpu.framework.program import Program, program_guard
    from paddle_tpu.framework.scope import Scope
    from paddle_tpu.observability import flight_recorder
    from paddle_tpu.parallel import elastic
    from paddle_tpu.parallel.mesh import DeviceMesh
    from paddle_tpu.parallel.process_world import ProcessWorld

    n = args.world
    mesh = DeviceMesh(jax.devices()[:n], {"dp": n})
    world = ProcessWorld(n)
    # arm the flight recorder: every rank's barrier phase transitions
    # beacon into <root>/dossiers (PTPU_DOSSIER_DIR overrides), so a
    # SIGKILL anywhere in the sweep leaves a dossier trail naming the
    # dead rank and phase — what the post-mortem asserts on
    flight_recorder.install(
        os.environ.get("PTPU_DOSSIER_DIR")
        or os.path.join(args.root, "dossiers"))

    class _MeshOnly:
        pass

    exe = _MeshOnly()
    exe.mesh = mesh

    def _save(generation, fault_env=None):
        arrays = world_atomic_arrays(generation)
        prog, startup = Program(), Program()
        scope = Scope()
        with program_guard(prog, startup):
            for name, val in arrays.items():
                prog.global_block().create_var(
                    name=name, shape=list(val.shape), dtype="float32",
                    persistable=True)
                sharding = (mesh.batch_sharding(val.ndim)
                            if name.startswith("sharded")
                            else mesh.replicated())
                scope.set_var(name, jax.device_put(np.asarray(val),
                                                   sharding))
        if fault_env is not None:
            os.environ["PTPU_FAULT_INJECT"] = fault_env
        return elastic.save_train_state(args.root, program=prog,
                                        scope=scope, executor=exe,
                                        step=generation, world=world,
                                        barrier_deadline_s=30)

    p0 = _save(0)                                # generation 0: committed
    assert p0 is not None, "generation 0 barrier must commit"
    _save(1, fault_env=args.fault or "")         # gen 1: may die anywhere
    return 0


# ---------------------------------------------------------------------------
# orchestrator
# ---------------------------------------------------------------------------

def _child_env(fault=None):
    env = dict(os.environ)
    env.pop("PTPU_FAULT_INJECT", None)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    if fault:
        env["PTPU_FAULT_INJECT"] = fault
    return env


def _child_argv(root, out, dp=2, steps=STEPS, snap_every=SNAP_EVERY,
                fault_if_fresh=None, world=0, fault_once=None):
    argv = [sys.executable, os.path.abspath(__file__), "--child",
            "--root", root, "--out", out, "--dp", str(dp),
            "--steps", str(steps), "--snap_every", str(snap_every)]
    if fault_if_fresh:
        argv += ["--fault_if_fresh", fault_if_fresh]
    if fault_once:
        argv += ["--fault_once", fault_once]
    if world:
        argv += ["--world", str(world)]
    return argv


def _losses(out_path):
    """last-write-wins per step: a resumed run re-appends its tail."""
    got = {}
    with open(out_path) as f:
        for line in f:
            row = json.loads(line)
            got[row["step"]] = row["loss"]
    return got


def orchestrate(args) -> int:
    from paddle_tpu.trainer import Supervisor
    if args.keep_root:
        work = args.keep_root
        shutil.rmtree(work, ignore_errors=True)
        os.makedirs(work)
    else:
        work = tempfile.mkdtemp(prefix="ptpu_recovery_")
    steps = args.steps

    print("== reference run (uninterrupted, dp=2) ==")
    ref_out = os.path.join(work, "ref.jsonl")
    rc = subprocess.run(_child_argv(os.path.join(work, "ref"), ref_out,
                                    steps=steps),
                        env=_child_env()).returncode
    assert rc == 0, f"reference run failed rc={rc}"
    ref = _losses(ref_out)
    assert sorted(ref) == list(range(steps)), ref

    print("== phase A: supervised SIGKILL mid-run, resume, exact parity ==")
    root_a = os.path.join(work, "a")
    out_a = os.path.join(work, "a.jsonl")
    sup = Supervisor(
        _child_argv(root_a, out_a, steps=steps,
                    fault_if_fresh=f"crash_at_step:{CRASH_STEP}"),
        max_restarts=2, backoff_s=0.2, env=_child_env())
    rc = sup.run()
    assert rc == 0, f"supervised run did not recover rc={rc}"
    assert sup.restarts >= 1 and sup.exit_codes[0] != 0, sup.exit_codes
    got = _losses(out_a)
    deltas = [abs(got[i] - ref[i]) for i in range(steps)]
    assert max(deltas) == 0.0, \
        f"resumed losses not bitwise-equal to reference: {deltas}"
    print(f"   exact parity over {steps} steps after "
          f"{sup.restarts} restart(s), exit codes {sup.exit_codes}")

    print("== phase B: SIGKILL mid-run, restart with dp resized 2 -> 4 ==")
    root_b = os.path.join(work, "b")
    out_b = os.path.join(work, "b.jsonl")
    rc = subprocess.run(
        _child_argv(root_b, out_b, steps=steps),
        env=_child_env(fault=f"crash_at_step:{CRASH_STEP}")).returncode
    assert rc != 0, "fault-injected run was supposed to die"
    rc = subprocess.run(_child_argv(root_b, out_b, dp=4, steps=steps),
                        env=_child_env()).returncode
    assert rc == 0, f"resized restart failed rc={rc}"
    got = _losses(out_b)
    deltas = [abs(got[i] - ref[i]) for i in range(steps)]
    assert max(deltas) <= ATOL_RESIZE, \
        f"dp4-resumed losses off reference by {max(deltas)}: {deltas}"
    print(f"   dp4 resume parity max |delta| = {max(deltas):.2e} "
          f"(bar {ATOL_RESIZE})")

    print("== phase C: SIGKILL DURING a snapshot write ==")
    from paddle_tpu.parallel import elastic
    root_c = os.path.join(work, "c")
    out_c = os.path.join(work, "c.jsonl")
    # offset 0: die at the very first staged byte of the step-2 snapshot
    rc = subprocess.run(
        _child_argv(root_c, out_c, steps=steps),
        env=_child_env(fault="crash_mid_save:0")).returncode
    assert rc != 0, "crash_mid_save run was supposed to die"
    assert elastic.latest_snapshot(root_c) is None, \
        "a snapshot interrupted at byte 0 must not be committed"
    rc = subprocess.run(_child_argv(root_c, out_c, steps=steps),
                        env=_child_env()).returncode
    assert rc == 0, f"restart after mid-save crash failed rc={rc}"
    got = _losses(out_c)
    deltas = [abs(got[i] - ref[i]) for i in range(steps)]
    assert max(deltas) == 0.0, \
        f"post-mid-save-crash losses not exact: {deltas}"
    print("   uncommitted snapshot skipped; restart exact")

    if args.keep_root:
        print(f"work dir kept at {work} (dp4-resized root: {root_b})")
    else:
        shutil.rmtree(work, ignore_errors=True)
    print("recovery smoke OK")
    return 0


def orchestrate_world(args) -> int:
    """The multi-rank phases (--world N): chief-commits barrier under
    real SIGKILLs of a non-chief rank mid-barrier and the chief
    mid-COMMIT, restart, fixed-seed loss parity vs the uninterrupted
    run. Keeps (under --keep_root) root `d` with a committed barrier
    snapshot and root `e` additionally holding the chief-kill's
    UNCOMMITTED snapshot dir — the run_ci.sh lint stanza's positive and
    negative --restore_dir targets."""
    from paddle_tpu.parallel import elastic
    n = args.world
    dp = n
    if args.keep_root:
        work = args.keep_root
        shutil.rmtree(work, ignore_errors=True)
        os.makedirs(work)
    else:
        work = tempfile.mkdtemp(prefix="ptpu_recovery_world_")
    steps = args.steps

    print(f"== world reference run (uninterrupted, dp={dp}, "
          f"{n}-rank barrier) ==")
    ref_out = os.path.join(work, "ref.jsonl")
    rc = subprocess.run(
        _child_argv(os.path.join(work, "ref"), ref_out, dp=dp,
                    steps=steps, world=n),
        env=_child_env()).returncode
    assert rc == 0, f"world reference run failed rc={rc}"
    ref = _losses(ref_out)
    assert sorted(ref) == list(range(steps)), ref
    ref_snap = elastic.latest_snapshot(os.path.join(work, "ref"))
    assert ref_snap is not None, "reference run committed no snapshot"
    marker_path = os.path.join(ref_snap, elastic.COMMIT_MARKER)
    marker = json.load(open(marker_path))
    assert marker["manifests"] == n, \
        f"barrier snapshot binds {marker['manifests']} manifests, " \
        f"expected {n}"

    def _kill_phase(tag, fault, expect_uncommitted):
        """Warm up a root to a committed barrier snapshot (steps/2),
        then run the full child armed with `fault` — it RESUMES from the
        committed snapshot and the designated rank dies at the next
        barrier — then restart unfaulted and demand bitwise parity."""
        root = os.path.join(work, tag)
        out = os.path.join(work, f"{tag}.jsonl")
        half = steps // 2
        rc = subprocess.run(
            _child_argv(root, out, dp=dp, steps=half, world=n),
            env=_child_env()).returncode
        assert rc == 0, f"{tag}: warm-up run failed rc={rc}"
        warm = elastic.latest_snapshot(root)
        assert warm is not None and \
            elastic.read_meta(warm)["step"] == half
        rc = subprocess.run(
            _child_argv(root, out, dp=dp, steps=steps, world=n,
                        fault_once=fault),
            env=_child_env()).returncode
        assert rc == -9, f"{tag}: child exited {rc}, expected SIGKILL " \
                         f"({fault})"
        # the kill happened strictly before a COMMIT marker: the warm-up
        # snapshot is still the latest committed one
        latest = elastic.latest_snapshot(root)
        assert latest is not None and \
            elastic.read_meta(latest)["step"] == half, \
            f"{tag}: a barrier killed pre-COMMIT must commit nothing new"
        uncommitted = [p for _, p in elastic.list_snapshots(
            root, committed_only=False) if not elastic.is_committed(p)]
        if expect_uncommitted:
            assert uncommitted, \
                f"{tag}: chief killed between rename and COMMIT must " \
                f"leave an uncommitted snapshot dir"
        rc = subprocess.run(
            _child_argv(root, out, dp=dp, steps=steps, world=n),
            env=_child_env()).returncode
        assert rc == 0, f"{tag}: restart failed rc={rc}"
        got = _losses(out)
        deltas = [abs(got[i] - ref[i]) for i in range(steps)]
        assert max(deltas) == 0.0, \
            f"{tag}: resumed losses not bitwise-equal: {deltas}"
        elastic.validate_snapshot(elastic.latest_snapshot(root))
        return [p for _, p in elastic.list_snapshots(
            root, committed_only=False) if not elastic.is_committed(p)]

    print("== phase D: SIGKILL non-chief rank 2 mid-barrier "
          "(crash_rank:2@ack), resume, exact parity ==")
    _kill_phase("d", "crash_rank:2@ack", expect_uncommitted=False)
    print("   rank-2 kill committed nothing; resumed run exact")

    print("== phase E: SIGKILL the CHIEF mid-COMMIT "
          "(crash_rank:0@commit), resume, exact parity ==")
    still = _kill_phase("e", "crash_rank:0@commit",
                        expect_uncommitted=True)
    assert still, "uncommitted leftover expected to remain on disk " \
                  "(the run_ci lint negative target)"
    print(f"   uncommitted leftover {still[0]} skipped; resume exact")

    if args.keep_root:
        print(f"work dir kept at {work} (committed: {work}/d, "
              f"uncommitted leftover: {still[0]})")
    else:
        shutil.rmtree(work, ignore_errors=True)
    print("world recovery smoke OK")
    return 0


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--child", action="store_true")
    p.add_argument("--atomic-child", action="store_true",
                   dest="atomic_child")
    p.add_argument("--world-atomic-child", action="store_true",
                   dest="world_atomic_child")
    p.add_argument("--root", default="")
    p.add_argument("--out", default="")
    p.add_argument("--dp", type=int, default=2)
    p.add_argument("--world", type=int, default=0,
                   help="simulated ProcessWorld size: children snapshot "
                        "through the chief-commits barrier; the "
                        "orchestrator runs the multi-rank kill phases")
    p.add_argument("--steps", type=int, default=STEPS)
    p.add_argument("--snap_every", type=int, default=SNAP_EVERY)
    p.add_argument("--fault_if_fresh", default="")
    p.add_argument("--fault_once", default="",
                   help="arm PTPU_FAULT_INJECT for exactly one attempt "
                        "(sentinel-file tracked; works for faults that "
                        "commit nothing, unlike --fault_if_fresh)")
    p.add_argument("--fault", default="")
    p.add_argument("--keep_root", default="",
                   help="orchestrator work dir to keep (the CI stanza "
                        "lints the resized root afterwards)")
    args = p.parse_args()
    if args.child:
        sys.exit(run_child(args))
    if args.atomic_child:
        sys.exit(run_atomic_child(args))
    if args.world_atomic_child:
        args.world = args.world or 4
        sys.exit(run_world_atomic_child(args))
    if args.world:
        sys.exit(orchestrate_world(args))
    sys.exit(orchestrate(args))


if __name__ == "__main__":
    main()
