#!/usr/bin/env python
"""Kill-the-process-mid-run recovery smoke (ROADMAP item 5 acceptance bar).

Orchestrates REAL process deaths through the elastic fault-injection hook
(PTPU_FAULT_INJECT, paddle_tpu/parallel/elastic.py) and asserts recovery:

  phase A  supervised preemption: a child training dp=2 SIGKILLs itself
           mid-run on its first attempt; trainer.Supervisor relaunches
           it; the resumed run restores the latest committed snapshot
           and its per-step fixed-seed losses match the uninterrupted
           reference run EXACTLY (bitwise — the snapshot carries the RNG
           run counter).
  phase B  dp-world resize: crash a dp=2 run, restart it with dp=4; the
           resumed losses match the reference within ATOL_RESIZE (fp32
           collectives regroup the mean across a different shard count —
           reduction-order ulps, the r09/r11 parity regime).
  phase C  crash DURING a snapshot write (SIGKILL at a byte offset of
           the staged payload): the surviving directory is uncommitted,
           restore falls back to the previous committed snapshot, and
           the relaunched run still reproduces the reference exactly.

Child modes (also used by tests/test_elastic.py):
  --child          one training run: restore-if-possible, train to
                   --steps, snapshot every --snap_every, append per-step
                   losses to --out as JSON lines
  --atomic-child   no-mesh snapshot writer for the crash-mid-save
                   atomicity property test: commit generation 0, then
                   save generation 1 (which PTPU_FAULT_INJECT may kill
                   at any byte offset)

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python tools/recovery_smoke.py
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

ATOL_RESIZE = 1e-5
STEPS = 8
SNAP_EVERY = 2
CRASH_STEP = 5


# ---------------------------------------------------------------------------
# child: one (resumable) training run
# ---------------------------------------------------------------------------

def _build_model():
    """EXACTLY tools/lint_program.py's `--model mnist --optimizer
    momentum` program, so the CI stanza can lint the restored program's
    sharded-state placement against the snapshots this child commits."""
    import paddle_tpu as pt
    from paddle_tpu import models
    loss = models.mnist.mlp()[0]
    pt.optimizer.MomentumOptimizer(0.1, momentum=0.9).minimize(loss)
    return loss


def _feed_for_step(i):
    import numpy as np
    rng = np.random.RandomState(1000 + i)
    return {"img": rng.rand(8, 784).astype("float32"),
            "label": rng.randint(0, 10, (8, 1)).astype("int64")}


def run_child(args) -> int:
    import jax

    import paddle_tpu as pt
    from paddle_tpu.parallel import ParallelExecutor, elastic
    from paddle_tpu.parallel.mesh import DeviceMesh
    from paddle_tpu.parallel.strategy import BuildStrategy, ReduceStrategy

    fresh = elastic.latest_snapshot(args.root) is None
    if args.fault_if_fresh and fresh:
        # self-arming fault: only the FIRST attempt crashes, so one
        # Supervisor argv covers crash and recovery
        os.environ["PTPU_FAULT_INJECT"] = args.fault_if_fresh

    with pt.core.unique_name.guard():
        loss = _build_model()
    bst = BuildStrategy()
    bst.reduce_strategy = ReduceStrategy.ReduceScatter
    mesh = DeviceMesh(jax.devices()[:args.dp], {"dp": args.dp})
    pexe = ParallelExecutor(loss_name=loss.name, build_strategy=bst,
                            mesh=mesh)
    pt.Executor().run(pt.default_startup_program())
    start = 0
    if not fresh:
        meta = elastic.restore_train_state(args.root, executor=pexe)
        start = int(meta["step"])
    with open(args.out, "a") as f:
        for i in range(start, args.steps):
            elastic.maybe_crash_at_step(i)
            val = float(pexe.run(feed=_feed_for_step(i),
                                 fetch_list=[loss])[0])
            f.write(json.dumps({"step": i, "loss": val}) + "\n")
            f.flush()
            if (i + 1) % args.snap_every == 0:
                elastic.save_train_state(args.root, executor=pexe,
                                         step=i + 1)
    return 0


# ---------------------------------------------------------------------------
# child: mesh-free snapshot writer (atomicity property test)
# ---------------------------------------------------------------------------

def run_atomic_child(args) -> int:
    import numpy as np

    from paddle_tpu.parallel import elastic

    # shapes/seed mirror tests/test_elastic.py _host_snapshot_args: the
    # parent checks surviving state against this exact generation 0
    rng = np.random.RandomState(7)
    arrays0 = {f"w_{k}": rng.randn(16, 4).astype("f4") for k in range(3)}
    arrays1 = {k: v + 1.0 for k, v in arrays0.items()}

    from paddle_tpu.framework.program import Program, program_guard
    from paddle_tpu.framework.scope import Scope

    def _save(arrays, step, fault_env=None):
        prog, startup = Program(), Program()
        scope = Scope()
        with program_guard(prog, startup):
            for name, val in arrays.items():
                prog.global_block().create_var(
                    name=name, shape=list(val.shape), dtype="float32",
                    persistable=True)
                scope.set_var(name, val)
        if fault_env is not None:
            os.environ["PTPU_FAULT_INJECT"] = fault_env
        elastic.save_train_state(args.root, program=prog, scope=scope,
                                 step=step)

    _save(arrays0, step=0)                       # generation 0: committed
    _save(arrays1, step=1, fault_env=args.fault or "")  # gen 1: may die
    return 0


# ---------------------------------------------------------------------------
# orchestrator
# ---------------------------------------------------------------------------

def _child_env(fault=None):
    env = dict(os.environ)
    env.pop("PTPU_FAULT_INJECT", None)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    if fault:
        env["PTPU_FAULT_INJECT"] = fault
    return env


def _child_argv(root, out, dp=2, steps=STEPS, snap_every=SNAP_EVERY,
                fault_if_fresh=None):
    argv = [sys.executable, os.path.abspath(__file__), "--child",
            "--root", root, "--out", out, "--dp", str(dp),
            "--steps", str(steps), "--snap_every", str(snap_every)]
    if fault_if_fresh:
        argv += ["--fault_if_fresh", fault_if_fresh]
    return argv


def _losses(out_path):
    """last-write-wins per step: a resumed run re-appends its tail."""
    got = {}
    with open(out_path) as f:
        for line in f:
            row = json.loads(line)
            got[row["step"]] = row["loss"]
    return got


def orchestrate(args) -> int:
    from paddle_tpu.trainer import Supervisor
    if args.keep_root:
        work = args.keep_root
        shutil.rmtree(work, ignore_errors=True)
        os.makedirs(work)
    else:
        work = tempfile.mkdtemp(prefix="ptpu_recovery_")
    steps = args.steps

    print("== reference run (uninterrupted, dp=2) ==")
    ref_out = os.path.join(work, "ref.jsonl")
    rc = subprocess.run(_child_argv(os.path.join(work, "ref"), ref_out,
                                    steps=steps),
                        env=_child_env()).returncode
    assert rc == 0, f"reference run failed rc={rc}"
    ref = _losses(ref_out)
    assert sorted(ref) == list(range(steps)), ref

    print("== phase A: supervised SIGKILL mid-run, resume, exact parity ==")
    root_a = os.path.join(work, "a")
    out_a = os.path.join(work, "a.jsonl")
    sup = Supervisor(
        _child_argv(root_a, out_a, steps=steps,
                    fault_if_fresh=f"crash_at_step:{CRASH_STEP}"),
        max_restarts=2, backoff_s=0.2, env=_child_env())
    rc = sup.run()
    assert rc == 0, f"supervised run did not recover rc={rc}"
    assert sup.restarts >= 1 and sup.exit_codes[0] != 0, sup.exit_codes
    got = _losses(out_a)
    deltas = [abs(got[i] - ref[i]) for i in range(steps)]
    assert max(deltas) == 0.0, \
        f"resumed losses not bitwise-equal to reference: {deltas}"
    print(f"   exact parity over {steps} steps after "
          f"{sup.restarts} restart(s), exit codes {sup.exit_codes}")

    print("== phase B: SIGKILL mid-run, restart with dp resized 2 -> 4 ==")
    root_b = os.path.join(work, "b")
    out_b = os.path.join(work, "b.jsonl")
    rc = subprocess.run(
        _child_argv(root_b, out_b, steps=steps),
        env=_child_env(fault=f"crash_at_step:{CRASH_STEP}")).returncode
    assert rc != 0, "fault-injected run was supposed to die"
    rc = subprocess.run(_child_argv(root_b, out_b, dp=4, steps=steps),
                        env=_child_env()).returncode
    assert rc == 0, f"resized restart failed rc={rc}"
    got = _losses(out_b)
    deltas = [abs(got[i] - ref[i]) for i in range(steps)]
    assert max(deltas) <= ATOL_RESIZE, \
        f"dp4-resumed losses off reference by {max(deltas)}: {deltas}"
    print(f"   dp4 resume parity max |delta| = {max(deltas):.2e} "
          f"(bar {ATOL_RESIZE})")

    print("== phase C: SIGKILL DURING a snapshot write ==")
    from paddle_tpu.parallel import elastic
    root_c = os.path.join(work, "c")
    out_c = os.path.join(work, "c.jsonl")
    # offset 0: die at the very first staged byte of the step-2 snapshot
    rc = subprocess.run(
        _child_argv(root_c, out_c, steps=steps),
        env=_child_env(fault="crash_mid_save:0")).returncode
    assert rc != 0, "crash_mid_save run was supposed to die"
    assert elastic.latest_snapshot(root_c) is None, \
        "a snapshot interrupted at byte 0 must not be committed"
    rc = subprocess.run(_child_argv(root_c, out_c, steps=steps),
                        env=_child_env()).returncode
    assert rc == 0, f"restart after mid-save crash failed rc={rc}"
    got = _losses(out_c)
    deltas = [abs(got[i] - ref[i]) for i in range(steps)]
    assert max(deltas) == 0.0, \
        f"post-mid-save-crash losses not exact: {deltas}"
    print("   uncommitted snapshot skipped; restart exact")

    if args.keep_root:
        print(f"work dir kept at {work} (dp4-resized root: {root_b})")
    else:
        shutil.rmtree(work, ignore_errors=True)
    print("recovery smoke OK")
    return 0


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--child", action="store_true")
    p.add_argument("--atomic-child", action="store_true",
                   dest="atomic_child")
    p.add_argument("--root", default="")
    p.add_argument("--out", default="")
    p.add_argument("--dp", type=int, default=2)
    p.add_argument("--steps", type=int, default=STEPS)
    p.add_argument("--snap_every", type=int, default=SNAP_EVERY)
    p.add_argument("--fault_if_fresh", default="")
    p.add_argument("--fault", default="")
    p.add_argument("--keep_root", default="",
                   help="orchestrator work dir to keep (the CI stanza "
                        "lints the resized root afterwards)")
    args = p.parse_args()
    if args.child:
        sys.exit(run_child(args))
    if args.atomic_child:
        sys.exit(run_atomic_child(args))
    sys.exit(orchestrate(args))


if __name__ == "__main__":
    main()
