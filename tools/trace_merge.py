#!/usr/bin/env python
"""Merge per-rank Chrome traces into ONE cross-rank timeline.

The distributed flight recorder tags every span a process-world rank
records with {world, rank, world_size} (tracing.rank_scope), and each
rank (or each process, on real multi-host hardware) exports its own
Chrome trace. This tool folds them into one timeline the way
chrome://tracing / Perfetto expects a distributed trace to be laid out:

- **rank → pid**: every rank becomes its own process lane, named
  "rank <r> (<world>)" via process_name metadata events;
- **phase → tid**: spans whose name matches a known protocol-phase
  family (barrier/<phase>, request/<phase>, pp_send//pp_recv) are
  grouped onto a stable per-phase thread lane with a thread_name
  metadata event, so the same phase lines up vertically across ranks
  and "who waited on whom" reads off the gaps; everything else keeps
  its recording thread's lane;
- **per-rank clock alignment**: perf_counter origins differ across
  processes. With `--align-span NAME` every input's timeline is shifted
  so its FIRST event of that name lands at the same merged timestamp
  (default `barrier/stage`: every rank records it for every snapshot
  serial; pass an empty string to disable). Within one process the
  shift is 0 by construction — the alignment is exercised, not faked.

Usage:
    python tools/trace_merge.py rankA.json rankB.json -o merged.json
    python tools/trace_merge.py one_ring_export.json -o merged.json
        # spans carry args.rank: the single file splits into rank lanes

Events without a rank tag land on pid --untagged-pid (default 999,
lane "untagged (host)").
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

#: name prefixes whose spans collapse onto one named thread lane per
#: (rank, phase family) — the "phase → tid" naming of the merged view.
#: "memory/" carries the r17 watermark COUNTER events (ph "C"): each
#: rank's memory levels plot on one lane under its span lanes.
PHASE_FAMILIES = ("barrier/", "request/", "pp_send/", "pp_recv/",
                  "elastic/", "engine/", "memory/")


def _load_events(path: str) -> List[dict]:
    with open(path) as f:
        doc = json.load(f)
    evs = doc.get("traceEvents", doc if isinstance(doc, list) else [])
    return [e for e in evs if e.get("ph") != "M"]   # re-derive metadata


def _rank_of(ev: dict) -> Optional[int]:
    args = ev.get("args") or {}
    r = args.get("rank")
    try:
        return int(r)
    except (TypeError, ValueError):
        return None


def _world_of(ev: dict) -> str:
    return str((ev.get("args") or {}).get("world", ""))


def _phase_tid(name: str) -> Optional[str]:
    for fam in PHASE_FAMILIES:
        if name.startswith(fam):
            return name if name.startswith(("barrier/", "request/")) \
                else fam.rstrip("/")
    return None


def _align_shift(events: List[dict], align_span: str) -> float:
    """Shift (us) that moves this input's first `align_span` event to
    t=0; 0.0 when the span is absent (nothing to align on)."""
    ts = [e["ts"] for e in events
          if e.get("name") == align_span and "ts" in e]
    return -min(ts) if ts else 0.0


def merge(inputs: List[str], align_span: str = "barrier/stage",
          untagged_pid: int = 999) -> dict:
    """The merged Chrome trace document (see module docstring)."""
    out_events: List[dict] = []
    pid_names: Dict[int, str] = {}
    tid_names: Dict[Tuple[int, int], str] = {}
    tid_alloc: Dict[Tuple[int, str], int] = {}

    def _tid_for(pid: int, key: str, pretty: str) -> int:
        k = (pid, key)
        if k not in tid_alloc:
            tid_alloc[k] = len([1 for (p, _) in tid_alloc if p == pid]) + 1
            tid_names[(pid, tid_alloc[k])] = pretty
        return tid_alloc[k]

    for path in inputs:
        events = _load_events(path)
        shift = _align_shift(events, align_span) if align_span else 0.0
        for ev in events:
            ev = dict(ev)
            rank = _rank_of(ev)
            if rank is None:
                pid = untagged_pid
                pid_names.setdefault(pid, "untagged (host)")
            else:
                pid = rank
                world = _world_of(ev)
                pid_names.setdefault(
                    pid, f"rank {rank}" + (f" ({world})" if world else ""))
            ev["pid"] = pid
            if "ts" in ev:
                ev["ts"] = ev["ts"] + shift
            fam = _phase_tid(str(ev.get("name", "")))
            if fam is not None:
                ev["tid"] = _tid_for(pid, f"phase:{fam}", fam)
            else:
                ev["tid"] = _tid_for(pid, f"thread:{ev.get('tid', 0)}",
                                     f"thread {ev.get('tid', 0)}")
            out_events.append(ev)

    meta = []
    for pid, name in sorted(pid_names.items()):
        meta.append({"ph": "M", "name": "process_name", "pid": pid,
                     "tid": 0, "args": {"name": name}})
    for (pid, tid), name in sorted(tid_names.items()):
        meta.append({"ph": "M", "name": "thread_name", "pid": pid,
                     "tid": tid, "args": {"name": name}})
    out_events.sort(key=lambda e: e.get("ts", 0.0))
    return {"traceEvents": meta + out_events, "displayTimeUnit": "ms"}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("inputs", nargs="+",
                    help="per-rank Chrome trace JSON files (or one "
                         "ring export with rank-tagged spans)")
    ap.add_argument("-o", "--out", required=True,
                    help="merged Chrome trace output path")
    ap.add_argument("--align-span", default="barrier/stage",
                    help="span name to align per-input clocks on "
                         "('' disables; default barrier/stage)")
    ap.add_argument("--untagged-pid", type=int, default=999)
    args = ap.parse_args(argv)
    for p in args.inputs:
        if not os.path.exists(p):
            print(f"trace_merge: no such input {p!r}", file=sys.stderr)
            return 2
    doc = merge(args.inputs, align_span=args.align_span,
                untagged_pid=args.untagged_pid)
    d = os.path.dirname(os.path.abspath(args.out))
    os.makedirs(d, exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(doc, f)
    n_ranks = len({e['pid'] for e in doc['traceEvents']
                   if e.get('ph') != 'M'})
    print(f"trace_merge: {len(doc['traceEvents'])} events, "
          f"{n_ranks} process lane(s) -> {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
