"""Roofline-cap reconciliation (VERDICT r4 #5).

Round 4 quoted HBM-roofline "MFU caps" derived from XLA's bytes-accessed —
and the committed NMT line (mfu 0.224) EXCEEDS its own quoted cap
(0.18-0.19). The contradiction is methodological: bytes-accessed is an
UPPER bound on true HBM traffic (it double-charges the VMEM-prefetch
overlay and multi-consumer fusion reads — PROF_r04 §2 measured 19.7 of
89.6 GB as prefetch double-count on the flagship), so a "cap" computed
from it is the LOWER end of an interval, not a ceiling.

This probe computes, for the three cap-quoted configs (LM d512, NMT,
flagship ResNet-50), the traffic INTERVAL:

  traffic_high = XLA cost-model bytes accessed (upper bound: overlays +
                 multi-consumer double-charges)
  traffic_low  = top-level entry census MINUS the copy-done/async-done
                 prefetch overlay (the attribute_bytes methodology) —
                 still an over-estimate of unique HBM bytes when a buffer
                 has several top-level consumers, but strictly tighter

and restates each cap as the interval
  mfu_cap in [flops / max(t_mxu, traffic_high/BW) / peak,
              flops / max(t_mxu, traffic_low /BW) / peak]
with the invariant: measured mfu <= cap_high * (1 + tunnel jitter).

    env PYTHONPATH=/root/.axon_site:/root/repo python tools/probe_caps.py
"""

from __future__ import annotations

import json
import os
import re
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from probe_common import (V5E_HBM_BPS, V5E_PEAK_TFLOPS,  # noqa: E402
                          hlo_shape_bytes as _shape_bytes, measure_step)

_SKIP = {"get-tuple-element", "bitcast", "parameter", "tuple", "constant",
         "after-all", "copy-start", "async-start"}


def entry_census(hlo: str):
    """(total_charged_bytes, prefetch_overlay_bytes) over top-level entry
    instructions, charging operands+outputs (attribute_bytes methodology,
    generalized to any program)."""
    cur = None
    defs = {}
    total = prefetch = 0
    for line in hlo.splitlines():
        mc = re.match(r"(ENTRY )?%?([\w.\-]+)\s*\([^)]*\)\s*->", line)
        if mc:
            cur = "ENTRY" if mc.group(1) else mc.group(2)
            continue
        if cur != "ENTRY":
            continue
        m = re.match(r"\s+%?([\w.\-]+)\s*=\s*(\([^=]*?\)|\S+)\s+([a-z\-]+)",
                     line)
        if not m:
            continue
        name, sh, op = m.groups()
        out_b = _shape_bytes(sh)
        defs[name] = out_b
        if op in _SKIP:
            continue
        if op in ("copy-done", "async-done"):
            prefetch += out_b
            continue
        call = line[m.end():]
        operands = re.findall(r"%([\w.\-]+)", call.split("metadata")[0])
        in_b = sum(defs[o] for o in dict.fromkeys(operands) if o in defs)
        total += in_b + out_b
    return total, prefetch


def cap_interval(flops, traffic_high, traffic_low):
    t_mxu = flops / (V5E_PEAK_TFLOPS)
    lo = flops / max(t_mxu, traffic_high / V5E_HBM_BPS) / V5E_PEAK_TFLOPS
    hi = flops / max(t_mxu, traffic_low / V5E_HBM_BPS) / V5E_PEAK_TFLOPS
    return round(lo, 3), round(hi, 3)


def _run(name, build, make_feed, iters=12):
    hlo_path = f"/tmp/caps_{name}.hlo"
    m = measure_step(build, make_feed, iters=iters, hlo_path=hlo_path)
    hlo = open(hlo_path).read()
    charged, overlay = entry_census(hlo)
    traffic_high = m["bytes_acc"]
    traffic_low = max(charged - overlay, 1.0)
    lo, hi = cap_interval(m["flops"], traffic_high, traffic_low)
    mfu = m["flops"] / m["step_s"] / V5E_PEAK_TFLOPS
    rec = {
        "config": name,
        "step_ms": round(m["step_s"] * 1e3, 2),
        "flops_G": round(m["flops"] / 1e9, 1),
        "traffic_GB": {
            "xla_bytes_accessed": round(traffic_high / 1e9, 2),
            "entry_census_charged": round(charged / 1e9, 2),
            "prefetch_overlay": round(overlay / 1e9, 2),
            "census_minus_overlay": round(traffic_low / 1e9, 2),
        },
        "achieved_GBps_vs_xla_bytes": round(
            traffic_high / m["step_s"] / 1e9, 1),
        "mfu_measured": round(mfu, 3),
        "mfu_cap_interval": [lo, hi],
        "measured_within_cap": bool(mfu <= hi * 1.05),
    }
    print(json.dumps(rec), flush=True)
    return rec


def main():
    import paddle_tpu as pt
    from paddle_tpu import models
    from paddle_tpu.models import transformer

    rng = np.random.RandomState(0)

    def build_lm():
        loss, _ = transformer.transformer_lm(
            vocab=32000, max_len=512, d_model=512, d_inner=2048,
            num_heads=8, num_layers=6, dropout=0.0)
        return loss, pt.optimizer.AdamOptimizer(learning_rate=1e-4)

    def feed_lm(b=16, t=512):
        return {"tokens": rng.randint(0, 32000, (b, t)).astype("int64"),
                "tokens@SEQLEN": np.full((b,), t, "int32"),
                "targets": rng.randint(0, 32000, (b, t)).astype("int64")}

    def build_nmt():
        loss, _ = transformer.transformer(
            src_vocab=16000, tgt_vocab=16000, max_len=256, d_model=512,
            d_inner=2048, num_heads=8, num_layers=4, dropout=0.0)
        return loss, pt.optimizer.AdamOptimizer(learning_rate=1e-4)

    def feed_nmt(b=16, t=256):
        return {"src": rng.randint(1, 16000, (b, t)).astype("int64"),
                "src@SEQLEN": np.full((b,), t, "int32"),
                "tgt": rng.randint(1, 16000, (b, t)).astype("int64"),
                "tgt@SEQLEN": np.full((b,), t, "int32"),
                "lbl": rng.randint(1, 16000, (b, t)).astype("int64")}

    def build_resnet():
        loss, acc, _ = models.resnet.resnet_imagenet(
            depth=50, is_test=False, data_format="NHWC", use_bf16=True)
        return loss, pt.optimizer.MomentumOptimizer(learning_rate=3e-3,
                                                    momentum=0.9)

    def feed_resnet(b=256):
        return {"img": rng.rand(b, 224, 224, 3).astype("float32"),
                "label": rng.randint(0, 1000, (b, 1)).astype("int64")}

    _run("lm6l_512d_bs16_T512", build_lm, feed_lm)
    _run("nmt4l_512d_bs16_T256", build_nmt, feed_nmt)
    _run("resnet50_bs256", build_resnet, feed_resnet, iters=8)


if __name__ == "__main__":
    main()
