"""Round-3 MFU attribution, part 3: where do the 77 GB/step go?

Dumps the optimized HLO of the compiled ResNet-50 train step and
summarizes traffic suspects: copies, transposes, big fp32 buffers,
select-and-scatter (maxpool bwd), plus per-category byte totals from the
cost analysis.

    env PYTHONPATH=/root/.axon_site:/root/repo python tools/profile_resnet3.py
"""

from __future__ import annotations

import collections
import json
import re

import numpy as np


def main():
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu import models

    batch = 256
    pt.reset_default_programs()
    pt.reset_global_scope()
    loss, acc, _ = models.resnet.resnet_imagenet(
        depth=50, is_test=False, data_format="NHWC", use_bf16=True)
    opt = pt.optimizer.MomentumOptimizer(learning_rate=3e-3, momentum=0.9)
    opt.minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())

    rng = np.random.RandomState(0)
    feed = {
        "img": jnp.asarray(rng.rand(batch, 224, 224, 3).astype("float32")),
        "label": jnp.asarray(rng.randint(0, 1000, (batch, 1)).astype("int64")),
    }
    compiled = exe._lookup_or_compile(
        pt.default_main_program(), feed, [loss.name], pt.global_scope())
    feed_vals = tuple(jnp.asarray(feed[n]) for n in compiled.feed_names)
    scope = pt.global_scope()
    ro_vals = tuple(scope.get(n) for n in compiled.ro_names)
    rw_vals = tuple(scope.get(n) for n in compiled.rw_names)
    ex = compiled.fn.lower(feed_vals, ro_vals, rw_vals,
                           np.uint32(0)).compile()
    hlo = ex.as_text()
    with open("/tmp/resnet_train_optimized.hlo", "w") as f:
        f.write(hlo)

    # shape -> bytes
    def shape_bytes(sh):
        m = re.match(r"(bf16|f32|f16|s32|u32|s8|u8|pred|s64|u64)\[([0-9,]*)\]",
                     sh)
        if not m:
            return 0
        it = {"bf16": 2, "f16": 2, "f32": 4, "s32": 4, "u32": 4, "s8": 1,
              "u8": 1, "pred": 1, "s64": 8, "u64": 8}[m.group(1)]
        dims = m.group(2)
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        return n * it

    op_bytes = collections.Counter()
    op_count = collections.Counter()
    big_f32 = []
    for line in hlo.splitlines():
        m = re.search(r"=\s+((?:bf16|f32|f16|s32|u32|s8|u8|pred|s64|u64)"
                      r"\[[0-9,]*\][^ ]*)\s+([a-z\-]+)", line)
        if not m:
            continue
        sh, op = m.group(1), m.group(2)
        b = shape_bytes(sh)
        op_bytes[op] += b
        op_count[op] += 1
        if sh.startswith("f32") and b > 50e6:
            big_f32.append((round(b / 1e6), op, line.strip()[:140]))

    top = op_bytes.most_common(15)
    print(json.dumps({
        "exp": "hlo_output_bytes_by_op",
        "top": [(op, round(b / 1e9, 2), op_count[op]) for op, b in top],
    }), flush=True)
    big_f32.sort(reverse=True)
    print(json.dumps({"exp": "big_f32_buffers",
                      "top10": big_f32[:10]}), flush=True)
    ca = ex.cost_analysis()
    ca = ca[0] if isinstance(ca, (list, tuple)) else ca
    keys = {k: v for k, v in ca.items()
            if "bytes" in k and isinstance(v, float) and v > 1e9}
    print(json.dumps({"exp": "cost_analysis_byte_keys", "keys": keys}),
          flush=True)


if __name__ == "__main__":
    main()
