"""probe_dgrad with hardened timing: cycles 4 DISTINCT input variants per
iteration (the same degenerate-benchmark rule the breadth suite applies)
and cross-checks wall time of the whole window. Supersedes the first
probe_dgrad run whose variant-A numbers (1667 TFLOP/s on a 197-peak chip)
were an identical-call artifact.

    env PYTHONPATH=/root/.axon_site:/root/repo python tools/probe_dgrad2.py
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

DN = ("NHWC", "HWIO", "NHWC")
NVAR = 4


def _sync(out):
    """Host-value realization is the ONLY trusted barrier through the
    axon tunnel (probe_common.py / bench.py methodology):
    block_until_ready returns early there. Fetch one scalar element of
    the final output — 4 bytes over the link, ordered after the whole
    queue."""
    x = out[0] if isinstance(out, (tuple, list)) else out
    return float(np.asarray(x[(0,) * x.ndim]))


def _time(fn, variants, iters=24, windows=4):
    """variants: list of arg-tuples cycled across iterations."""
    for v in variants:
        _sync(fn(*v))
    best = None
    for _ in range(windows):
        t0 = time.time()
        out = None
        for i in range(iters):
            out = fn(*variants[i % len(variants)])
        _sync(out)
        dt = (time.time() - t0) / iters
        best = dt if best is None else min(best, dt)
    return best


def _cost(fn, args):
    ex = jax.jit(fn).lower(*args).compile()
    ca = ex.cost_analysis()
    ca = ca[0] if isinstance(ca, (list, tuple)) else (ca or {})
    return (float(ca.get("bytes accessed", 0.0)),
            float(ca.get("flops", 0.0)))


def _report(name, fn, variants):
    jfn = jax.jit(fn)
    t = _time(jfn, variants)
    b, f = _cost(fn, variants[0])
    row = {"variant": name, "ms": round(t * 1e3, 3),
           "bytes_MB": round(b / 1e6, 1), "flops_G": round(f / 1e9, 2),
           "achieved_GBps": round(b / t / 1e9, 1) if b else None,
           "achieved_TFLOPs": round(f / t / 1e12, 2) if f else None,
           "n_distinct_inputs": len(variants)}
    print(json.dumps(row), flush=True)
    return row


def conv_fwd(x, w, stride=1):
    return jax.lax.conv_general_dilated(
        x, w, window_strides=(stride, stride), padding="SAME",
        dimension_numbers=DN)


def main():
    rng = np.random.RandomState(0)
    results = {}

    B, HW, Ci, Co = 256, 56, 256, 64

    def mk(shape):
        return [jnp.asarray(rng.rand(*shape).astype("float32"),
                            jnp.bfloat16) for _ in range(NVAR)]

    dys = mk((B, HW, HW, Co))
    ws = mk((1, 1, Ci, Co))
    xs = mk((B, HW, HW, Ci))

    def dgrad_conv_1x1(dy, w, x):
        _, vjp = jax.vjp(lambda x_: conv_fwd(x_, w), x)
        return vjp(dy)[0]

    def dgrad_dot_1x1(dy, w, x):
        dy2 = dy.reshape(-1, Co)
        w2 = w.reshape(Ci, Co)
        dx = jax.lax.dot_general(dy2, w2, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        return dx.astype(dy.dtype).reshape(B, HW, HW, Ci)

    print("== A: 1x1 dgrad [256,56,56,64] -> [256,56,56,256]", flush=True)
    var3 = list(zip(dys, ws, xs))
    a_conv = _report("dgrad_1x1_conv_emitter", dgrad_conv_1x1, var3)
    a_dot = _report("dgrad_1x1_dot_general", dgrad_dot_1x1, var3)
    results["dgrad_1x1_speedup_dot_over_conv"] = round(
        a_conv["ms"] / a_dot["ms"], 3)

    def vjp_conv_1x1(x, w, dy):
        y, vjp = jax.vjp(lambda x_, w_: conv_fwd(x_, w_), x, w)
        return (y,) + vjp(dy)

    def vjp_dot_1x1(x, w, dy):
        x2 = x.reshape(-1, Ci)
        w2 = w.reshape(Ci, Co)
        dy2 = dy.reshape(-1, Co)

        def f(x2_, w2_):
            return jax.lax.dot_general(
                x2_, w2_, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32).astype(x2_.dtype)
        y2, vjp = jax.vjp(f, x2, w2)
        dx2, dw2 = vjp(dy2)
        return (y2.reshape(B, HW, HW, Co), dx2.reshape(B, HW, HW, Ci),
                dw2.reshape(1, 1, Ci, Co))

    print("== A': 1x1 fwd+bwd vjp", flush=True)
    var_xwd = list(zip(xs, ws, dys))
    av_conv = _report("vjp_1x1_conv_emitter", vjp_conv_1x1, var_xwd)
    av_dot = _report("vjp_1x1_dot_general", vjp_dot_1x1, var_xwd)
    results["vjp_1x1_speedup_dot_over_conv"] = round(
        av_conv["ms"] / av_dot["ms"], 3)

    # ---- B: 3x3 dgrad at 56x56, 64->64 ----------------------------------
    C3 = 64
    xs3 = mk((B, HW, HW, C3))
    ws3 = mk((3, 3, C3, C3))
    dys3 = mk((B, HW, HW, C3))

    def dgrad_conv_3x3(dy, w, x):
        _, vjp = jax.vjp(lambda x_: conv_fwd(x_, w), x)
        return vjp(dy)[0]

    def dgrad_im2col_3x3(dy, w, x):
        patches = jax.lax.conv_general_dilated_patches(
            dy, (3, 3), (1, 1), "SAME", dimension_numbers=DN)
        wf = jnp.flip(w, (0, 1))
        wr = jnp.transpose(wf, (3, 0, 1, 2)).reshape(9 * C3, C3)
        dx = jax.lax.dot_general(
            patches.reshape(-1, 9 * C3), wr, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dx.astype(dy.dtype).reshape(B, HW, HW, C3)

    print("== B: 3x3 dgrad 64ch @56x56", flush=True)
    var3b = list(zip(dys3, ws3, xs3))
    b_conv = _report("dgrad_3x3_conv_emitter", dgrad_conv_3x3, var3b)
    b_im2col = _report("dgrad_3x3_im2col_dot", dgrad_im2col_3x3, var3b)
    results["dgrad_3x3_speedup_im2col_over_conv"] = round(
        b_conv["ms"] / b_im2col["ms"], 3)

    print(json.dumps({"exp": "dgrad_probe2_summary", **results}),
          flush=True)


if __name__ == "__main__":
    main()
