"""Probe: what bounds prefetcher link utilization? (round-4 follow-up)

Measures, all in ONE tunnel session: raw uint8 link at 1/2/3 concurrent
streams, float->uint8 conversion cost, and drain-only DevicePrefetcher
rates at several (stage_threads, capacity) settings.

    env PYTHONPATH=/root/.axon_site:/root/repo python tools/probe_prefetch2.py
"""
import json
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np


def main(batch=128):
    import jax

    from paddle_tpu.data.feeder import staging_specs  # noqa: F401
    from paddle_tpu.data.prefetch import DevicePrefetcher

    img_u8 = (np.random.RandomState(0).rand(batch, 224, 224, 3) * 255
              ).astype("uint8")
    nbytes = img_u8.nbytes

    d = jax.device_put(img_u8)
    _ = np.asarray(d[0, 0, 0, 0])

    out = {}

    def put_one(x):
        h = jax.device_put(x)
        _ = np.asarray(h[0, 0, 0, 0])
        return h

    for streams in (1, 2, 3):
        pool = ThreadPoolExecutor(max_workers=streams)
        reps = 6
        best = None
        for _ in range(2):
            t0 = time.time()
            futs = [pool.submit(put_one, img_u8) for _ in range(reps)]
            for f in futs:
                f.result()
            dt = time.time() - t0
            best = dt if best is None else min(best, dt)
        out[f"link_MBps_{streams}stream"] = round(
            nbytes * reps / best / 1e6, 2)
        pool.shutdown()

    # conversion cost on the staging thread (fp32 batch -> uint8 wire)
    img_f32 = np.random.RandomState(1).rand(batch, 224, 224, 3).astype(
        "float32")
    t0 = time.time()
    for _ in range(5):
        w = (img_f32 * 255.0).astype("uint8")
    out["convert_ms_per_batch"] = round((time.time() - t0) / 5 * 1e3, 1)

    # drain-only prefetcher rate (no training step): the pipeline's own
    # ceiling at each setting
    import paddle_tpu as pt  # noqa: F401  (registers staging helpers)
    host_batches = [
        {"img": np.random.RandomState(i).rand(batch, 224, 224, 3)
         .astype("float32"),
         "label": np.random.RandomState(i).randint(0, 1000, (batch, 1))
         .astype("int64")}
        for i in range(4)
    ]
    specs = {"img": ("uint8", 1.0 / 255.0)}

    def feed_iter():
        for i in range(12):
            yield host_batches[i % 4]

    for threads, cap in ((1, 4), (2, 4), (3, 6), (4, 8)):
        best = None
        for _ in range(2):
            pf = iter(DevicePrefetcher(feed_iter, capacity=cap,
                                       staging=specs,
                                       stage_threads=threads))
            first = next(pf)  # warm
            _ = np.asarray(first["img"][0, 0, 0, 0])
            t0 = time.time()
            n = 0
            last = None
            for b in pf:
                last = b
                n += 1
            _ = np.asarray(last["img"][0, 0, 0, 0])
            dt = time.time() - t0
            rate = n * batch / dt
            best = rate if best is None else max(best, rate)
        out[f"drain_imgs_per_s_t{threads}_c{cap}"] = round(best, 2)
        out[f"drain_wire_MBps_t{threads}_c{cap}"] = round(
            best * 224 * 224 * 3 / 1e6, 2)

    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
