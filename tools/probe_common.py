"""Shared harness for the step-attribution probes (probe_lstm/probe_nmt).

One place for the build → compile → cost_analysis → best-of-N timing
boilerplate, so fixes to timing or cost-model handling land once.
"""
from __future__ import annotations

import time
from typing import Callable, Dict, Tuple

import numpy as np

V5E_PEAK_TFLOPS = 197e12
V5E_HBM_BPS = 819e9

# dtype byte widths for parsing XLA shape strings — the ONE copy shared by
# the probes (probe_caps) and the comm-structure tests. Covers every XLA
# scalar type that can appear in a typed shape (ADVICE r5 #4); an
# unrecognized typed-shape token RAISES instead of silently counting 0
# bytes (which would let byte-balance assertions pass/fail misleadingly
# if dtypes drift).
HLO_ITEM_BYTES = {"pred": 1,
                  "s2": 1, "u2": 1, "s4": 1, "u4": 1,     # sub-byte types
                  "s8": 1, "u8": 1, "s16": 2, "u16": 2,   # pack >= 1 byte
                  "s32": 4, "u32": 4, "s64": 8, "u64": 8,
                  "f8e4m3": 1, "f8e4m3fn": 1, "f8e4m3b11fnuz": 1,
                  "f8e4m3fnuz": 1, "f8e5m2": 1, "f8e5m2fnuz": 1,
                  "f8e3m4": 1, "f8e8m0fnu": 1,
                  "bf16": 2, "f16": 2, "f32": 4, "f64": 8,
                  "c64": 8, "c128": 16}

# typed-shape tokens that are legitimately byte-free
_HLO_ZERO_BYTE_TYPES = frozenset({"token", "opaque"})


def hlo_shape_bytes(sh: str) -> int:
    """Total bytes of every typed array in one HLO shape string (tuple
    shapes sum their elements). Raises on a typed-shape token whose
    element type is not in HLO_ITEM_BYTES."""
    import re
    total = 0
    matched_any = False
    for m in re.finditer(r"([a-zA-Z][a-zA-Z0-9]*)\[([0-9,]*)\]", sh):
        matched_any = True
        dtype = m.group(1)
        if dtype in _HLO_ZERO_BYTE_TYPES:
            continue
        if dtype not in HLO_ITEM_BYTES:
            raise ValueError(
                f"hlo_shape_bytes: unrecognized element type {dtype!r} in "
                f"shape string {sh!r}; add it to HLO_ITEM_BYTES")
        n = 1
        for d in m.group(2).split(","):
            if d:
                n *= int(d)
        total += n * HLO_ITEM_BYTES[dtype]
    if not matched_any and "[" in sh:
        raise ValueError(
            f"hlo_shape_bytes: no typed shape recognized in {sh!r} "
            f"(dynamic dims or unexpected syntax?)")
    return total


def collective_census(hlo: str) -> Dict[str, list]:
    """{kind: [(output_bytes, line)]} for every collective instruction in a
    compiled (per-device) HLO module. Async pairs are counted once, at the
    -start; tuple-shaped outputs (all-to-all emits one operand per peer,
    with /*index=N*/ comments past 5 elements) sum their elements."""
    import re
    out: Dict[str, list] = {}
    for line in hlo.splitlines():
        # tuple shapes may nest one paren level INSIDE the tuple: TPU
        # layouts print as {1,0:T(8,128)} — [^()] alone would stop there
        # and silently drop the instruction from the census
        m = re.match(
            r"\s*(?:ROOT )?%?[\w.\-]+ = "
            r"(\((?:[^()]|\([^()]*\))*\)|\S+)\s+"
            r"(all-reduce|reduce-scatter|all-gather|collective-permute|"
            r"all-to-all)(-start|-done)?\(", line)
        if not m:
            continue
        if m.group(3) == "-done":
            continue
        kind = m.group(2)
        out.setdefault(kind, []).append((hlo_shape_bytes(m.group(1)), line))
    return out


# Per-device bytes each collective puts on the interconnect, as a function
# of its (per-device) OUTPUT bytes in the partitioned HLO — the standard
# ring-algorithm accounting, shared by the comm-structure tests and the
# benchmark's grad_bytes_on_wire field so both quote the same model:
#   all-reduce out=n:        ring RS+AG, sends 2n(N-1)/N
#   reduce-scatter out=c:    input N*c, sends c(N-1)
#   all-gather out=n:        contributes n/N, sends n(N-1)/N
#   all-to-all out total=t:  keeps its own chunk, sends t(N-1)/N
#   collective-permute out=n: sends n
def collective_wire_bytes(kind: str, out_bytes: int, n_devices: int) -> float:
    n = n_devices
    return {
        "all-reduce": 2.0 * out_bytes * (n - 1) / n,
        "reduce-scatter": float(out_bytes) * (n - 1),
        "all-gather": float(out_bytes) * (n - 1) / n,
        "all-to-all": float(out_bytes) * (n - 1) / n,
        "collective-permute": float(out_bytes),
    }[kind]


def census_wire_bytes(census: Dict[str, list], n_devices: int,
                      min_bytes: int = 0) -> float:
    """Total per-device interconnect bytes for one step, from a
    collective_census; instructions with output below `min_bytes` can be
    excluded (scalar loss/metric reductions)."""
    total = 0.0
    for kind, items in census.items():
        for b, _ in items:
            if b >= min_bytes:
                total += collective_wire_bytes(kind, b, n_devices)
    return total


def measure_step(build: Callable[[], Tuple], make_feed: Callable[[], Dict],
                 iters: int = 15, windows: int = 3, hlo_path: str = None):
    """build() -> (loss_var, optimizer); make_feed() -> feed dict.

    Returns {step_s, flops, bytes_acc} with flops/bytes from XLA's own
    cost model for the compiled train step (0.0 when the backend does not
    report them) and step_s the best-of-`windows` mean over `iters` steps,
    host-value realization as the only trusted barrier (see bench.py).
    """
    import jax.numpy as jnp
    import paddle_tpu as pt

    pt.reset_default_programs()
    pt.reset_global_scope()
    with pt.core.unique_name.guard():
        loss, opt = build()
        opt.minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    feed = {k: jnp.asarray(v) for k, v in make_feed().items()}

    prog, scope = pt.default_main_program(), pt.global_scope()
    compiled = exe._lookup_or_compile(prog, feed, [loss.name], scope)
    feed_vals = tuple(jnp.asarray(feed[n]) for n in compiled.feed_names)
    ro_vals = tuple(scope.get(n) for n in compiled.ro_names)
    rw_vals = tuple(scope.get(n) for n in compiled.rw_names)
    ex = compiled.fn.lower(feed_vals, ro_vals, rw_vals,
                           np.uint32(0)).compile()
    if hlo_path:
        with open(hlo_path, "w") as f:
            f.write(ex.as_text())
    ca = ex.cost_analysis()
    ca = ca[0] if isinstance(ca, (list, tuple)) else ca
    ca = ca or {}
    bytes_acc = float(ca.get("bytes accessed", 0.0))
    flops = float(ca.get("flops", 0.0))

    o = exe.run(feed=feed, fetch_list=[loss], return_numpy=False)
    float(np.asarray(o[0]).ravel()[0])  # compile + drain
    best = None
    for _ in range(windows):
        t0 = time.time()
        fetched = []
        for _ in range(iters):
            o = exe.run(feed=feed, fetch_list=[loss], return_numpy=False)
            fetched.append(o[0])
        float(np.asarray(fetched[-1]).ravel()[0])
        dt = (time.time() - t0) / iters
        best = dt if best is None else min(best, dt)
    return {"step_s": best, "flops": flops, "bytes_acc": bytes_acc}


def roofline_fields(step_s: float, flops: float, bytes_acc: float) -> Dict:
    """The shared attribution fields; None where the cost model gave 0."""
    out = {
        "step_ms": round(step_s * 1e3, 2),
        "bytes_GB": round(bytes_acc / 1e9, 2) if bytes_acc else None,
        "flops_G": round(flops / 1e9, 1) if flops else None,
        "intensity_flops_per_byte":
            round(flops / bytes_acc, 1) if flops and bytes_acc else None,
        "ideal_mxu_ms":
            round(flops / V5E_PEAK_TFLOPS * 1e3, 3) if flops else None,
        "ideal_hbm_ms":
            round(bytes_acc / V5E_HBM_BPS * 1e3, 3) if bytes_acc else None,
        "mfu": round(flops / step_s / V5E_PEAK_TFLOPS, 4) if flops else None,
    }
    return out
