"""Shared harness for the step-attribution probes (probe_lstm/probe_nmt).

The analytic models that used to live here — the HLO byte parser, the
collective ring wire model, the per-op flop/byte roofline — were promoted
to `paddle_tpu/framework/costs.py` (r12): the framework owns ONE copy the
pipeline partitioner, the cost ledger, and the planner can all query.
This module re-exports them under their historical names so every probe,
bench, and census test keeps importing from one place, and keeps the
measurement-side boilerplate (build -> compile -> cost_analysis ->
best-of-N timing) that only makes sense in the tools tree.
"""
from __future__ import annotations

import time
from typing import Callable, Dict

import numpy as np

from paddle_tpu.framework.costs import (  # noqa: F401
    HLO_ITEM_BYTES, V5E_HBM_BPS, V5E_PEAK_TFLOPS, census_wire_bytes,
    collective_census, collective_wire_bytes, hlo_shape_bytes,
    op_cost_flops_bytes, op_time_cost, program_flops_bytes, roofline_fields)


def measure_step(build: Callable, make_feed: Callable[[], Dict],
                 iters: int = 15, windows: int = 3, hlo_path: str = None):
    """build() -> (loss_var, optimizer); make_feed() -> feed dict.

    Returns {step_s, flops, bytes_acc} with flops/bytes from XLA's own
    cost model for the compiled train step (0.0 when the backend does not
    report them) and step_s the best-of-`windows` mean over `iters` steps,
    host-value realization as the only trusted barrier (see bench.py).
    """
    import jax.numpy as jnp
    import paddle_tpu as pt

    pt.reset_default_programs()
    pt.reset_global_scope()
    with pt.core.unique_name.guard():
        loss, opt = build()
        opt.minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    feed = {k: jnp.asarray(v) for k, v in make_feed().items()}

    prog, scope = pt.default_main_program(), pt.global_scope()
    compiled = exe._lookup_or_compile(prog, feed, [loss.name], scope)
    feed_vals = tuple(jnp.asarray(feed[n]) for n in compiled.feed_names)
    ro_vals = tuple(scope.get(n) for n in compiled.ro_names)
    rw_vals = tuple(scope.get(n) for n in compiled.rw_names)
    ex = compiled.fn.lower(feed_vals, ro_vals, rw_vals,
                           np.uint32(0)).compile()
    if hlo_path:
        with open(hlo_path, "w") as f:
            f.write(ex.as_text())
    ca = ex.cost_analysis()
    ca = ca[0] if isinstance(ca, (list, tuple)) else ca
    ca = ca or {}
    bytes_acc = float(ca.get("bytes accessed", 0.0))
    flops = float(ca.get("flops", 0.0))

    o = exe.run(feed=feed, fetch_list=[loss], return_numpy=False)
    float(np.asarray(o[0]).ravel()[0])  # compile + drain
    best = None
    for _ in range(windows):
        t0 = time.time()
        fetched = []
        for _ in range(iters):
            o = exe.run(feed=feed, fetch_list=[loss], return_numpy=False)
            fetched.append(o[0])
        float(np.asarray(fetched[-1]).ravel()[0])
        dt = (time.time() - t0) / iters
        best = dt if best is None else min(best, dt)
    return {"step_s": best, "flops": flops, "bytes_acc": bytes_acc}
