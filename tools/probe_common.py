"""Shared harness for the step-attribution probes (probe_lstm/probe_nmt).

One place for the build → compile → cost_analysis → best-of-N timing
boilerplate, so fixes to timing or cost-model handling land once.
"""
from __future__ import annotations

import time
from typing import Callable, Dict, Tuple

import numpy as np

V5E_PEAK_TFLOPS = 197e12
V5E_HBM_BPS = 819e9

# dtype byte widths for parsing XLA shape strings — the ONE copy shared by
# the probes (probe_caps) and the comm-structure tests. Covers every XLA
# scalar type that can appear in a typed shape (ADVICE r5 #4); an
# unrecognized typed-shape token RAISES instead of silently counting 0
# bytes (which would let byte-balance assertions pass/fail misleadingly
# if dtypes drift).
HLO_ITEM_BYTES = {"pred": 1,
                  "s2": 1, "u2": 1, "s4": 1, "u4": 1,     # sub-byte types
                  "s8": 1, "u8": 1, "s16": 2, "u16": 2,   # pack >= 1 byte
                  "s32": 4, "u32": 4, "s64": 8, "u64": 8,
                  "f8e4m3": 1, "f8e4m3fn": 1, "f8e4m3b11fnuz": 1,
                  "f8e4m3fnuz": 1, "f8e5m2": 1, "f8e5m2fnuz": 1,
                  "f8e3m4": 1, "f8e8m0fnu": 1,
                  "bf16": 2, "f16": 2, "f32": 4, "f64": 8,
                  "c64": 8, "c128": 16}

# typed-shape tokens that are legitimately byte-free
_HLO_ZERO_BYTE_TYPES = frozenset({"token", "opaque"})


def hlo_shape_bytes(sh: str) -> int:
    """Total bytes of every typed array in one HLO shape string (tuple
    shapes sum their elements). Raises on a typed-shape token whose
    element type is not in HLO_ITEM_BYTES."""
    import re
    total = 0
    matched_any = False
    for m in re.finditer(r"([a-zA-Z][a-zA-Z0-9]*)\[([0-9,]*)\]", sh):
        matched_any = True
        dtype = m.group(1)
        if dtype in _HLO_ZERO_BYTE_TYPES:
            continue
        if dtype not in HLO_ITEM_BYTES:
            raise ValueError(
                f"hlo_shape_bytes: unrecognized element type {dtype!r} in "
                f"shape string {sh!r}; add it to HLO_ITEM_BYTES")
        n = 1
        for d in m.group(2).split(","):
            if d:
                n *= int(d)
        total += n * HLO_ITEM_BYTES[dtype]
    if not matched_any and "[" in sh:
        raise ValueError(
            f"hlo_shape_bytes: no typed shape recognized in {sh!r} "
            f"(dynamic dims or unexpected syntax?)")
    return total


def collective_census(hlo: str) -> Dict[str, list]:
    """{kind: [(output_bytes, line)]} for every collective instruction in a
    compiled (per-device) HLO module. Async pairs are counted once, at the
    -start; tuple-shaped outputs (all-to-all emits one operand per peer,
    with /*index=N*/ comments past 5 elements) sum their elements."""
    import re
    out: Dict[str, list] = {}
    for line in hlo.splitlines():
        # tuple shapes may nest one paren level INSIDE the tuple: TPU
        # layouts print as {1,0:T(8,128)} — [^()] alone would stop there
        # and silently drop the instruction from the census
        m = re.match(
            r"\s*(?:ROOT )?%?[\w.\-]+ = "
            r"(\((?:[^()]|\([^()]*\))*\)|\S+)\s+"
            r"(all-reduce|reduce-scatter|all-gather|collective-permute|"
            r"all-to-all)(-start|-done)?\(", line)
        if not m:
            continue
        if m.group(3) == "-done":
            continue
        kind = m.group(2)
        out.setdefault(kind, []).append((hlo_shape_bytes(m.group(1)), line))
    return out


# Per-device bytes each collective puts on the interconnect, as a function
# of its (per-device) OUTPUT bytes in the partitioned HLO — the standard
# ring-algorithm accounting, shared by the comm-structure tests and the
# benchmark's grad_bytes_on_wire field so both quote the same model:
#   all-reduce out=n:        ring RS+AG, sends 2n(N-1)/N
#   reduce-scatter out=c:    input N*c, sends c(N-1)
#   all-gather out=n:        contributes n/N, sends n(N-1)/N
#   all-to-all out total=t:  keeps its own chunk, sends t(N-1)/N
#   collective-permute out=n: sends n
def collective_wire_bytes(kind: str, out_bytes: int, n_devices: int) -> float:
    n = n_devices
    return {
        "all-reduce": 2.0 * out_bytes * (n - 1) / n,
        "reduce-scatter": float(out_bytes) * (n - 1),
        "all-gather": float(out_bytes) * (n - 1) / n,
        "all-to-all": float(out_bytes) * (n - 1) / n,
        "collective-permute": float(out_bytes),
    }[kind]


def census_wire_bytes(census: Dict[str, list], n_devices: int,
                      min_bytes: int = 0) -> float:
    """Total per-device interconnect bytes for one step, from a
    collective_census; instructions with output below `min_bytes` can be
    excluded (scalar loss/metric reductions)."""
    total = 0.0
    for kind, items in census.items():
        for b, _ in items:
            if b >= min_bytes:
                total += collective_wire_bytes(kind, b, n_devices)
    return total


# ---------------------------------------------------------------------------
# Analytic per-op cost model — the balancing signal for the pipeline
# partitioner (framework/passes.py pipeline_partition_pass) and the
# per-stage compute model of tools/probe_bubble.py. Costs are RELATIVE
# (batch dims unknown until feed time use `nominal_batch`); the roofline
# combine max(flops/peak, bytes/bw) uses the same v5e constants as the
# probes so one number means one thing everywhere.
# ---------------------------------------------------------------------------

# ops that are pure markers / bookkeeping: zero device cost
_ZERO_COST_OPS = frozenset({"pp_send", "pp_recv", "feed", "fetch"})

# per-output-element flop weights for transcendental-ish elementwise ops
_ELEMENTWISE_FLOPS = {"softmax": 5.0, "exp": 4.0, "log": 4.0, "tanh": 6.0,
                      "sigmoid": 5.0, "relu": 1.0, "sqrt": 4.0, "pow": 4.0,
                      "elementwise_pow": 4.0, "gelu": 8.0,
                      "layer_norm": 8.0, "batch_norm": 6.0,
                      "softmax_with_cross_entropy": 8.0,
                      "cross_entropy": 4.0, "dropout": 2.0}


def _var_numel(block, name, nominal_batch):
    try:
        v = block.var(name)
    except Exception:
        return 0
    shape = getattr(v, "shape", None) or ()
    n = 1
    for d in shape:
        n *= (nominal_batch if d == -1 else int(d))
    return n


def _var_shape(block, name, nominal_batch):
    try:
        v = block.var(name)
    except Exception:
        return None
    shape = getattr(v, "shape", None)
    if shape is None:
        return None
    return [nominal_batch if d == -1 else int(d) for d in shape]


def op_cost_flops_bytes(op, block, nominal_batch: int = 8) -> Tuple[float,
                                                                    float]:
    """(flops, bytes) estimate for one program op, from declared var shapes
    (-1 batch dims resolved to `nominal_batch` — the model only needs to be
    RELATIVELY right to balance contiguous stages)."""
    if op.type in _ZERO_COST_OPS:
        return 0.0, 0.0
    in_n = sum(_var_numel(block, n, nominal_batch)
               for n in op.input_names())
    out_n = sum(_var_numel(block, n, nominal_batch)
                for n in op.output_names())
    bytes_ = 4.0 * (in_n + out_n)
    t = op.type
    if t in ("mul", "matmul"):
        xs = _var_shape(block, op.inputs["X"][0], nominal_batch)
        k = 1.0
        if xs:
            k = float(xs[-2] if op.attrs.get("transpose_X") and len(xs) >= 2
                      else xs[-1])
        return 2.0 * out_n * k, bytes_
    if t in ("conv2d", "conv3d", "conv2d_transpose", "conv3d_transpose",
             "depthwise_conv2d"):
        # filter is [num_filters, cin/groups, k...] in both layouts, so
        # per-output-element work = 2 * numel(filter) / num_filters
        fn = _var_numel(block, op.inputs["Filter"][0], nominal_batch)
        fs = _var_shape(block, op.inputs["Filter"][0], nominal_batch)
        nf = float(fs[0]) if fs else 1.0
        return 2.0 * out_n * (fn / max(nf, 1.0)), bytes_
    if t in ("dynamic_lstm", "fused_lstm", "dynamic_gru", "fused_gru"):
        wn = sum(_var_numel(block, n, nominal_batch)
                 for slot in ("Weight", "WeightX", "WeightH")
                 for n in op.inputs.get(slot, []))
        return 2.0 * max(out_n, in_n) * max(wn, 1) ** 0.5, bytes_
    if t == "lookup_table":
        return float(out_n), bytes_
    return _ELEMENTWISE_FLOPS.get(t, 1.0) * out_n, bytes_


def op_time_cost(flops: float, bytes_: float) -> float:
    """Roofline combine of one op's (flops, bytes): seconds on the v5e
    peak — whichever engine bounds it."""
    return max(flops / V5E_PEAK_TFLOPS, bytes_ / V5E_HBM_BPS)


def measure_step(build: Callable[[], Tuple], make_feed: Callable[[], Dict],
                 iters: int = 15, windows: int = 3, hlo_path: str = None):
    """build() -> (loss_var, optimizer); make_feed() -> feed dict.

    Returns {step_s, flops, bytes_acc} with flops/bytes from XLA's own
    cost model for the compiled train step (0.0 when the backend does not
    report them) and step_s the best-of-`windows` mean over `iters` steps,
    host-value realization as the only trusted barrier (see bench.py).
    """
    import jax.numpy as jnp
    import paddle_tpu as pt

    pt.reset_default_programs()
    pt.reset_global_scope()
    with pt.core.unique_name.guard():
        loss, opt = build()
        opt.minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    feed = {k: jnp.asarray(v) for k, v in make_feed().items()}

    prog, scope = pt.default_main_program(), pt.global_scope()
    compiled = exe._lookup_or_compile(prog, feed, [loss.name], scope)
    feed_vals = tuple(jnp.asarray(feed[n]) for n in compiled.feed_names)
    ro_vals = tuple(scope.get(n) for n in compiled.ro_names)
    rw_vals = tuple(scope.get(n) for n in compiled.rw_names)
    ex = compiled.fn.lower(feed_vals, ro_vals, rw_vals,
                           np.uint32(0)).compile()
    if hlo_path:
        with open(hlo_path, "w") as f:
            f.write(ex.as_text())
    ca = ex.cost_analysis()
    ca = ca[0] if isinstance(ca, (list, tuple)) else ca
    ca = ca or {}
    bytes_acc = float(ca.get("bytes accessed", 0.0))
    flops = float(ca.get("flops", 0.0))

    o = exe.run(feed=feed, fetch_list=[loss], return_numpy=False)
    float(np.asarray(o[0]).ravel()[0])  # compile + drain
    best = None
    for _ in range(windows):
        t0 = time.time()
        fetched = []
        for _ in range(iters):
            o = exe.run(feed=feed, fetch_list=[loss], return_numpy=False)
            fetched.append(o[0])
        float(np.asarray(fetched[-1]).ravel()[0])
        dt = (time.time() - t0) / iters
        best = dt if best is None else min(best, dt)
    return {"step_s": best, "flops": flops, "bytes_acc": bytes_acc}


def roofline_fields(step_s: float, flops: float, bytes_acc: float) -> Dict:
    """The shared attribution fields; None where the cost model gave 0."""
    out = {
        "step_ms": round(step_s * 1e3, 2),
        "bytes_GB": round(bytes_acc / 1e9, 2) if bytes_acc else None,
        "flops_G": round(flops / 1e9, 1) if flops else None,
        "intensity_flops_per_byte":
            round(flops / bytes_acc, 1) if flops and bytes_acc else None,
        "ideal_mxu_ms":
            round(flops / V5E_PEAK_TFLOPS * 1e3, 3) if flops else None,
        "ideal_hbm_ms":
            round(bytes_acc / V5E_HBM_BPS * 1e3, 3) if bytes_acc else None,
        "mfu": round(flops / step_s / V5E_PEAK_TFLOPS, 4) if flops else None,
    }
    return out
