"""Decode roofline attribution (VERDICT r4 #3): what fraction of the
HBM-bandwidth decode bound does each BENCH_GEN config achieve, and where do
the per-step bytes go?

Decode is HBM-bound: every generated token must stream the parameters and
the live KV cache through the chip. This probe computes, per config:

  - structural_bytes_per_step: bf16 params + one full KV-cache read (the
    attention) + one cache write — the floor no decode formulation beats
    while the cache layout is dense;
  - xla_bytes_per_step: XLA cost-model bytes for the compiled generate
    graph divided by gen_len (amortizes the prologue);
  - bound_tokens_per_sec = batch / (xla_bytes_per_step / HBM_BW) and the
    achieved fraction at the measured tokens/s;
  - the same fraction against the structural floor, which says how much a
    better formulation (not a faster chip) could still win.

    env PYTHONPATH=/root/.axon_site:/root/repo python tools/probe_gen.py
"""

from __future__ import annotations

import json
import time

import numpy as np

V5E_HBM_BPS = 819e9

VOCAB, D, DI, NH, NL = 32000, 512, 2048, 8, 6


def _param_bytes():
    """bf16 bytes of every weight the decode step streams: 6 layers of
    (qkvo projs + 2 ffn mats + 2 LN) + tok_emb row gather + lm_head."""
    per_layer = 4 * D * D + D * DI + DI * D + 4 * D
    # tok_emb is a one-hot matmul in the decode graph: the whole [V, D]
    # table streams per step (the graph's actual formulation); lm_head too
    return 2 * (NL * per_layer + VOCAB * D + D * VOCAB)


def _cache_traffic_per_step(batch, beam, T, dtype_bytes=4):
    """One attention read of k+v caches across layers + the one-hot write's
    full read+write (the current formulation rewrites the whole cache)."""
    cache = batch * beam * T * D * dtype_bytes          # one [B,K,T,H]
    read_attn = 2 * NL * cache
    write_onehot = 2 * NL * 2 * cache                   # read + write, k+v
    return read_attn, write_onehot, cache


def measure(batch, gen_len, beam, iters=3):
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.core import unique_name
    from paddle_tpu.models import transformer

    pt.reset_default_programs()
    pt.reset_global_scope()
    with unique_name.guard():
        seqs, scores = transformer.transformer_lm_generate(
            vocab=VOCAB, max_gen=gen_len, d_model=D, d_inner=DI,
            num_heads=NH, num_layers=NL, bos_id=1, beam_size=beam)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    feed = {"prompt": jnp.asarray(np.full((batch, 1), 1, "int64"))}
    out = exe.run(feed=feed, fetch_list=[seqs])[0]
    assert np.asarray(out).shape == (batch, gen_len, beam)

    ca = exe.cost_analysis(feed=feed, fetch_list=[seqs]) or {}
    total_bytes = float(ca.get("bytes accessed", 0.0))

    best = None
    for _ in range(3):
        t0 = time.time()
        for _ in range(iters):
            out = exe.run(feed=feed, fetch_list=[seqs])[0]
        np.asarray(out)
        dt = (time.time() - t0) / iters
        best = dt if best is None else min(best, dt)

    tokens_per_sec = batch * gen_len / best
    xla_step_bytes = total_bytes / gen_len
    p_bytes = _param_bytes()
    read_attn, write_onehot, cache1 = _cache_traffic_per_step(
        batch, beam, gen_len)
    structural = p_bytes + read_attn + 2 * NL * cache1 / gen_len  # DUS write

    bound_structural = batch / (structural / V5E_HBM_BPS)
    rec = {
        "config": f"lm6l_512d_bs{batch}_gen{gen_len}_beam{beam}",
        "tokens_per_sec": round(tokens_per_sec, 1),
        "ms_per_step": round(best / gen_len * 1e3, 3),
        # diagnostic only: XLA's cost model underreports while-loop bodies
        # (~1/loop-count of the real traffic), so no bound is derived
        # from it
        "xla_bytes_per_step_MB_diagnostic": round(xla_step_bytes / 1e6, 1),
        "model_bytes_per_step_MB": {
            "params_bf16": round(p_bytes / 1e6, 1),
            "kv_attention_read": round(read_attn / 1e6, 1),
            "kv_onehot_write_readwrite_legacy": round(write_onehot / 1e6,
                                                      1),
            "structural_floor_dus_write": round(structural / 1e6, 1),
        },
        # THE committed metric: achieved fraction of the HBM-bandwidth
        # decode bound at the structural byte model (params + one cache
        # read + one row write per step)
        "decode_bound_tokens_per_sec": round(bound_structural, 1),
        "fraction_of_decode_bound": round(
            tokens_per_sec / bound_structural, 3),
    }
    print(json.dumps(rec), flush=True)
    return rec


def main():
    import jax
    if jax.devices()[0].platform == "cpu":
        measure(2, 4, 1, iters=1)
        return
    measure(16, 64, 1)
    measure(64, 64, 1)
    measure(16, 64, 4)


if __name__ == "__main__":
    main()
