"""Autoregressive generation throughput: the KV-cache decode scan on TPU.

The serving-side counterpart of the training benchmarks: tokens/sec for
the compiled generation graph (one lax.scan, per-layer KV caches in the
carry) at the flagship LM shape, greedy and beam-4.

    env PYTHONPATH=/root/.axon_site:/root/repo \
        python tools/bench_generate.py | tee BENCH_GEN_r04.json
"""

from __future__ import annotations

import json
import time

import numpy as np


def measure(batch, gen_len, beam, iters=3):
    import paddle_tpu as pt
    from paddle_tpu.core import unique_name
    from paddle_tpu.models import transformer

    pt.reset_default_programs()
    pt.reset_global_scope()
    with unique_name.guard():
        seqs, scores = transformer.transformer_lm_generate(
            vocab=32000, max_gen=gen_len, d_model=512, d_inner=2048,
            num_heads=8, num_layers=6, bos_id=1, beam_size=beam)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    feed = {"prompt": np.full((batch, 1), 1, "int64")}
    out = exe.run(feed=feed, fetch_list=[seqs])[0]  # compile + drain
    assert np.asarray(out).shape == (batch, gen_len, beam)

    best = None
    for _ in range(3):
        t0 = time.time()
        for _ in range(iters):
            out = exe.run(feed=feed, fetch_list=[seqs])[0]
        np.asarray(out)  # host realization bounds the timed dispatches
        dt = (time.time() - t0) / iters
        best = dt if best is None else min(best, dt)

    import jax
    dev = jax.devices()[0]
    rec = {
        "config": f"lm6l_512d_bs{batch}_gen{gen_len}_beam{beam}",
        "tokens_per_sec": round(batch * gen_len / best, 1),
        # per decode STEP (scan tick) — batch-independent; divide
        # 1000/tokens_per_sec for per-token amortized latency
        "ms_per_step": round(best / gen_len * 1e3, 3),
        "unit": "generated tokens/sec",
        "device_kind": getattr(dev, "device_kind", str(dev)),
    }
    print(json.dumps(rec), flush=True)
    return rec


def measure_nmt(batch, src_len, gen_len, beam, iters=3):
    """Encoder-decoder generation: encode once + cached beam decode."""
    import paddle_tpu as pt
    from paddle_tpu.core import unique_name
    from paddle_tpu.models import transformer

    pt.reset_default_programs()
    pt.reset_global_scope()
    with unique_name.guard():
        seqs, scores = transformer.transformer_generate(
            src_vocab=16000, tgt_vocab=16000, max_src_len=src_len,
            max_gen=gen_len, d_model=512, d_inner=2048, num_heads=8,
            num_layers=4, bos_id=0, eos_id=-1, beam_size=beam)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.RandomState(0)
    feed = {"src": rng.randint(1, 16000, (batch, src_len)).astype("int64"),
            "src@SEQLEN": np.full((batch,), src_len, "int32")}
    out = exe.run(feed=feed, fetch_list=[seqs])[0]
    assert np.asarray(out).shape == (batch, gen_len, beam)

    best = None
    for _ in range(3):
        t0 = time.time()
        for _ in range(iters):
            out = exe.run(feed=feed, fetch_list=[seqs])[0]
        np.asarray(out)
        dt = (time.time() - t0) / iters
        best = dt if best is None else min(best, dt)

    import jax
    dev = jax.devices()[0]
    rec = {
        "config": (f"nmt4l_512d_bs{batch}_src{src_len}"
                   f"_gen{gen_len}_beam{beam}"),
        "tokens_per_sec": round(batch * gen_len / best, 1),
        "ms_per_step": round(best / gen_len * 1e3, 3),
        "unit": "generated tokens/sec",
        "device_kind": getattr(dev, "device_kind", str(dev)),
    }
    print(json.dumps(rec), flush=True)
    return rec


def main():
    import jax
    on_accel = jax.devices()[0].platform != "cpu"
    if on_accel:
        measure(16, 64, 1)
        measure(64, 64, 1)
        measure(16, 64, 4)
        measure_nmt(16, 64, 32, 4)
    else:
        measure(2, 4, 1, iters=1)


if __name__ == "__main__":
    main()
