"""Two-tier host-offload bench (ISSUE r23): paged-KV spill, ZeRO-offload
optimizer state, and the planner's stash-to-host pricing, measured end
to end through framework/offload.py's shared pinned pool + transfer
stream.

The capacity claim, measured: at FIXED device KV pool bytes, on the r20
saturated trace, the two-tier engine (suspended admission + host spill
with prefetch) sustains >= 1.5x the device-only engine's admitted
concurrency, with decode output TOKEN-IDENTICAL per request to the
unconstrained-pool baseline, and the spill wire bytes PREDICTED from
the eviction/reload counters reconciling with the transfer stream's
measured bytes EXACTLY (r08/r11 discipline) on every benched cell.

Cells:

- kv_two_tier: device-pool sweep (admitted-concurrency + tokens/s
  curves vs device-pool bytes) x {device_only, two_tier}, saturated
  r20 trace shape, 16 tick slots both sides.
- optimizer_offload: ZeRO-offload optimizer state on a dp=8 train
  loop — loss bitwise-identical offload on/off, device optimizer
  bytes == 0 between steps, measured overlap fraction of the d2h
  against the host-side step gap.
- stash_to_host: the memory planner's third candidate priced on two
  programs — one whose PCIe round-trip CANNOT hide inside the compute
  window (the planner must refuse it) and one wide enough that it
  hides; plus a shadow-transfer measurement (real stash-sized bytes
  round-tripped on the stream during real executed steps) for the
  measured overlap fraction.

CPU-mesh caveat, stated plainly: jit consumes every argument at
dispatch, so the per-bucket streamed residency the costs.predict
offload section prices needs the TPU runtime; what IS measurable here
— and is asserted — is the between-step host residency (device census
optimizer_state == 0), bitwise loss identity, the exact wire-byte
census, and the overlap of the stream's copies against host-side work.

    JAX_PLATFORMS=cpu python tools/bench_offload.py          # full,
                                              writes BENCH_OFFLOAD_r23.json
    JAX_PLATFORMS=cpu python tools/bench_offload.py --smoke  # CI stanza
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from bench_serve_kv import _BLOCK_SIZE, _DIMS, _MAX_LEN, _trace  # noqa: E402

_TICK_SLOTS = 16
_HOST_BLOCKS = 64


def _host_tier():
    from paddle_tpu.serving import HostTierConfig
    return HostTierConfig(host_blocks=_HOST_BLOCKS, prefetch_distance=2,
                          rotate_quantum=8)


def _run_kv_cell(trace, prefixes, scope, n_blocks, two_tier):
    """One saturated-trace run at a fixed device pool: all requests
    offered up front (the backlog never empties until the tail), so
    mean admitted concurrency over backlogged ticks IS the pool-limited
    ceiling. Returns (row, per-request token streams)."""
    from paddle_tpu.framework import offload as _offload
    from paddle_tpu.serving import PagedKVEngine

    _offload.reset_offload()
    eng = PagedKVEngine(n_slots=_TICK_SLOTS, max_len=_MAX_LEN,
                        block_size=_BLOCK_SIZE, n_blocks=n_blocks,
                        scope=scope,
                        host_tier=_host_tier() if two_tier else None,
                        **_DIMS)
    warm = [eng.submit([1], max_new=1)]
    warm += [eng.submit(list(p), max_new=1) for p in prefixes]
    eng.run_until_idle()
    assert all(r.done for r in warm)
    eng.n_ticks = eng.busy_slot_ticks = eng.total_slot_ticks = 0
    eng.tokens_out = 0
    eng.ht_d2h_bytes = eng.ht_h2d_bytes = 0
    eng.pager.host_evictions = eng.pager.host_reloads = 0
    eng.pager.host_prefetch_hits = eng.pager.host_prefetch_misses = 0

    order = [eng.submit(prompt, max_new)
             for _, prompt, max_new in trace]
    done, active_curve, backlog_curve = [], [], []
    t0 = time.time()
    while eng.n_active or eng.n_pending:
        backlogged = eng.n_pending > 0
        done.extend(eng.step())
        n = eng.n_active
        if n:
            active_curve.append(n)
            if backlogged:
                backlog_curve.append(n)
    makespan = time.time() - t0

    curve = np.asarray(active_curve, np.float64)
    s = eng.pager.stats()
    eng.pager.pool.check()
    row = {
        "n_blocks": n_blocks,
        "device_pool_bytes": int(eng._kv_bytes_static),
        "two_tier": bool(two_tier),
        "n_requests": len(done),
        "tokens_per_sec": round(sum(len(r.tokens) for r in done)
                                / makespan, 1),
        "makespan_s": round(makespan, 3),
        "admitted_concurrency_under_backlog": round(
            float(np.mean(backlog_curve)), 2) if backlog_curve
            else round(float(curve.mean()), 2),
        "admitted_concurrency_peak": int(curve.max()) if len(curve)
            else 0,
    }
    if two_tier:
        ht = s["host_tier"]
        per = eng._ht_per_block_bytes
        pred_d2h = ht["host_evictions"] * per
        pred_h2d = ht["host_reloads"] * per
        eng.pager.check_two_tier()
        row.update({
            "host_tier": ht,
            "offload_d2h_bytes": int(eng.ht_d2h_bytes),
            "offload_h2d_bytes": int(eng.ht_h2d_bytes),
            "predicted_d2h_bytes": int(pred_d2h),
            "predicted_h2d_bytes": int(pred_h2d),
            # the r08/r11 exactness discipline: predicted wire bytes
            # (eviction/reload counters x the measured per-block spill
            # size) == the stream's measured bytes, EXACTLY
            "census_exact": bool(pred_d2h == eng.ht_d2h_bytes
                                 and pred_h2d == eng.ht_h2d_bytes),
            "prefetch_hit_rate": ht["prefetch_hit_rate"],
        })
    return row, [list(r.tokens) for r in order]


def _bench_kv(n_requests, smoke):
    """The device-pool sweep. Reference = an unconstrained pool (every
    request admits immediately); its token streams are the identity
    baseline for every constrained cell, offload on or off."""
    import paddle_tpu as pt

    pt.reset_default_programs()
    pt.reset_global_scope()
    scope = pt.global_scope()
    rng = np.random.RandomState(20)
    trace, prefixes = _trace(rng, n_requests, 0.001, "saturated")

    big = _TICK_SLOTS * (_MAX_LEN // _BLOCK_SIZE) + 1   # unconstrained
    _, ref_tokens = _run_kv_cell(trace, prefixes, scope, big, False)

    pools = (13,) if smoke else (9, 13, 17, 25)
    sweep, identical, exact = [], True, True
    for n_blocks in pools:
        dev_row, dev_tokens = _run_kv_cell(trace, prefixes, scope,
                                           n_blocks, False)
        two_row, two_tokens = _run_kv_cell(trace, prefixes, scope,
                                           n_blocks, True)
        cell_ident = (two_tokens == ref_tokens
                      and dev_tokens == ref_tokens)
        identical = identical and cell_ident
        exact = exact and two_row["census_exact"]
        ratio = (two_row["admitted_concurrency_under_backlog"]
                 / max(dev_row["admitted_concurrency_under_backlog"],
                       1e-9))
        sweep.append({
            "device_only": dev_row, "two_tier": two_row,
            "decode_token_identical": bool(cell_ident),
            "two_tier_over_device_admitted_concurrency": round(ratio, 2),
        })
    anchor = sweep[0]   # the tightest benched pool anchors the claim
    return {
        "trace": {"mode": "saturated", "n_requests": n_requests},
        "tick_slots": _TICK_SLOTS,
        "host_tier": {"host_blocks": _HOST_BLOCKS,
                      "prefetch_distance": 2, "rotate_quantum": 8},
        "sweep": sweep,
        "claims": {
            "decode_token_identical_all_cells": bool(identical),
            "census_exact_all_cells": bool(exact),
            "two_tier_admitted_concurrency_ge_1p5x_at_anchor": bool(
                anchor["two_tier_over_device_admitted_concurrency"]
                >= 1.5),
        },
    }


def _bench_optimizer(smoke):
    """ZeRO-offload optimizer state: loss identity, between-step host
    residency, measured overlap fraction."""
    import jax
    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.framework import offload as _offload
    from paddle_tpu.framework.scope import Scope
    from paddle_tpu.parallel import ParallelExecutor
    from paddle_tpu.parallel.mesh import DeviceMesh
    from paddle_tpu.parallel.strategy import BuildStrategy, ReduceStrategy

    d = 64 if smoke else 256
    steps = 4 if smoke else 8

    def _train(offload):
        _offload.reset_offload()
        pt.reset_default_programs()
        prog = pt.Program()
        start = pt.Program()
        with pt.program_guard(prog, start):
            x = layers.data("x", shape=[d])
            label = layers.data("label", shape=[1], dtype="int64")
            h = layers.fc(x, size=2 * d, act="relu")
            logits = layers.fc(h, size=10)
            loss = layers.mean(
                layers.softmax_with_cross_entropy(logits, label))
            pt.optimizer.AdamOptimizer(0.01).minimize(loss)
        scope = Scope()
        pt.Executor().run(program=start, scope=scope)
        bst = BuildStrategy()
        bst.reduce_strategy = ReduceStrategy.Reduce
        bst.offload_optimizer_state = offload
        exe = ParallelExecutor(loss_name=loss.name,
                               mesh=DeviceMesh(jax.devices(), {"dp": 8}),
                               build_strategy=bst, main_program=prog,
                               scope=scope)
        rng = np.random.RandomState(11)
        losses, waits = [], []
        t0 = time.perf_counter()
        for _ in range(steps):
            feed = {"x": rng.rand(16, d).astype("float32"),
                    "label": rng.randint(0, 10, (16, 1)).astype("int64")}
            out = exe.run(feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
            ho = getattr(exe, "_host_opt", None)
            if ho is not None:
                waits.append(ho.last_restore_wait_s)
        wall = time.perf_counter() - t0
        ho = getattr(exe, "_host_opt", None)
        return losses, wall, ho, waits, scope

    base_losses, base_wall, _, _, _ = _train(False)
    off_losses, off_wall, ho, waits, scope = _train(True)
    stream = _offload.shared_stream()
    busy = stream.counters()["busy_s"]
    total_wait = sum(waits[1:])         # step 1+: a prior d2h in flight
    overlap = max(0.0, 1.0 - total_wait / max(busy, 1e-9))
    host_bytes = _offload.shared_host_pool().used_bytes("optimizer")
    return {
        "model": {"d": d, "layers": 2, "optimizer": "adam",
                  "reduce": "zero1", "dp": 8},
        "steps": steps,
        "loss_bitwise_identical": bool(base_losses == off_losses),
        "optimizer_state_host_resident_between_steps": bool(
            ho is not None and ho.offloaded and host_bytes > 0),
        "host_optimizer_bytes": int(host_bytes),
        "bytes_per_direction_per_step": int(ho.bytes_per_direction),
        "roundtrips": int(ho.roundtrips),
        "restore_wait_s_total": round(total_wait, 6),
        "stream_busy_s_total": round(busy, 6),
        "measured_overlap_fraction": round(overlap, 4),
        "wall_s": {"offload_off": round(base_wall, 3),
                   "offload_on": round(off_wall, 3)},
        "cpu_mesh_caveat": (
            "overlap is measured against HOST-side work (next-batch "
            "prep + dispatch assembly) on a CPU mesh where jit consumes "
            "all arguments at dispatch; the per-bucket device-side "
            "residency costs.predict prices needs the TPU runtime"),
    }


def _bench_stash(smoke):
    """The planner's stash-to-host candidate, priced on two programs —
    one the PCIe roofline must REFUSE, one wide enough to hide — plus a
    shadow-transfer measurement of the stream overlapping real steps."""
    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.framework import memory_plan as _mp
    from paddle_tpu.framework import offload as _offload

    def _mlp(d):
        pt.reset_default_programs()
        pt.reset_global_scope()
        x = layers.data("x", shape=[d])
        label = layers.data("label", shape=[1], dtype="int64")
        h = layers.fc(x, size=2 * d, act="relu")
        h = layers.fc(h, size=2 * d, act="relu")
        logits = layers.fc(h, size=10)
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, label))
        pt.optimizer.SGDOptimizer(0.1).minimize(loss)
        return pt.default_main_program(), loss

    def _decision(d):
        prog, _ = _mlp(d)
        planned = _mp.plan_program(prog, nominal_batch=64,
                                   stash_to_host=True)
        rec = _mp.plan_report(planned).get("remat") or {}
        cand = next((c for c in rec.get("candidates", ())
                     if c.get("policy") == "stash_to_host"), None)
        return {"d_model": d, "chosen": rec.get("chosen"),
                "executed": rec.get("executed"),
                "candidate": cand}

    narrow = _decision(64)          # transfer >> window: must refuse
    wide = _decision(2048 if smoke else 4096)   # window > transfer

    # shadow transfer: round-trip real stash-sized bytes on the stream
    # while real steps execute, and measure how much of the copy hid
    prog, loss = _mlp(64)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.RandomState(5)
    feed = {"x": rng.rand(64, 64).astype("float32"),
            "label": rng.randint(0, 10, (64, 1)).astype("int64")}
    exe.run(feed=feed, fetch_list=[loss])          # compile
    stash_bytes = int((narrow["candidate"] or {}).get(
        "stash_freed_bytes", 0)) or (1 << 20)
    pool = _offload.shared_host_pool()
    stream = _offload.shared_stream()
    buf = pool.alloc((max(stash_bytes // 4, 1),), np.float32, "stash")
    src = np.ones(buf.array.shape, np.float32)
    waits, busys = [], []
    for _ in range(3 if smoke else 5):
        b0 = stream.counters()["busy_s"]
        t_d2h = stream.submit("d2h",
                              lambda: np.copyto(buf.array, src),
                              buf.nbytes, tag="stash-shadow")
        t_h2d = stream.submit("h2d", lambda: buf.array.copy(),
                              buf.nbytes, tag="stash-shadow")
        exe.run(feed=feed, fetch_list=[loss])
        w0 = time.perf_counter()
        t_d2h.wait(30)
        t_h2d.wait(30)
        waits.append(time.perf_counter() - w0)
        busys.append(stream.counters()["busy_s"] - b0)
    pool.free(buf)
    total_wait, total_busy = sum(waits), sum(busys)
    overlap = max(0.0, 1.0 - total_wait / max(total_busy, 1e-9))
    return {
        "refused_cell": narrow,
        "hidden_cell": wide,
        "planner_refuses_unhidden_transfer": bool(
            narrow["chosen"] != "stash_to_host"
            and narrow["candidate"] is not None
            and not narrow["candidate"]["fits_budget"]),
        "planner_accepts_hidden_transfer": bool(
            wide["chosen"] == "stash_to_host"
            and wide["executed"] == "advisory"),
        "shadow_transfer": {
            "bytes_per_direction": int(buf.nbytes),
            "wait_s_total": round(total_wait, 6),
            "stream_busy_s_total": round(total_busy, 6),
            "measured_overlap_fraction": round(overlap, 4),
        },
        "cpu_mesh_caveat": (
            "the chosen stash-to-host plan is ADVISORY on this backend "
            "(decision + pricing recorded, transfer not lowered — "
            "ROADMAP 5(a) tracks the TPU lowering); the overlap "
            "fraction above is measured on a REAL stash-sized "
            "round-trip riding the shared stream beside real executed "
            "steps, which is the mechanism the lowered path will use"),
    }


def bench(smoke=False):
    n_requests = 12 if smoke else 40
    kv = _bench_kv(n_requests, smoke)
    opt = _bench_optimizer(smoke)
    stash = _bench_stash(smoke)
    out = {
        "bench": "offload", "round": 23, "smoke": bool(smoke),
        "model": dict(_DIMS, max_len=_MAX_LEN),
        "kv_two_tier": kv,
        "optimizer_offload": opt,
        "stash_to_host": stash,
        "notes": (
            "two_tier trades tokens/s for admitted concurrency on this "
            "CPU backend: the spill gathers share the compute cores "
            "that also run the decode tick, so the eviction path costs "
            "throughput here that a TPU host DMA engine would not. The "
            "claim under test is the ADMISSION ceiling at fixed device "
            "pool bytes — decode stays token-identical while several "
            "times the device-only ceiling is in flight — plus the "
            "exact wire-byte census and the overlap fractions, all of "
            "which transfer to the TPU runtime; absolute tokens/s "
            "does not."),
        "claims": {
            **kv["claims"],
            "optimizer_loss_bitwise_identical": bool(
                opt["loss_bitwise_identical"]),
            "optimizer_state_host_resident_between_steps": bool(
                opt["optimizer_state_host_resident_between_steps"]),
            "planner_refuses_unhidden_stash": bool(
                stash["planner_refuses_unhidden_transfer"]),
            "planner_accepts_hidden_stash": bool(
                stash["planner_accepts_hidden_transfer"]),
        },
    }
    return out


def main():
    smoke = "--smoke" in sys.argv
    out = bench(smoke=smoke)
    doc = json.dumps(out, indent=1)
    print(doc, flush=True)
    if not smoke:
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        with open(os.path.join(repo, "BENCH_OFFLOAD_r23.json"),
                  "w") as f:
            f.write(doc + "\n")
    ok = out["claims"]
    assert ok["decode_token_identical_all_cells"], \
        "two-tier decode diverged from the unconstrained baseline"
    assert ok["census_exact_all_cells"], \
        "predicted offload wire bytes != measured stream bytes"
    assert ok["two_tier_admitted_concurrency_ge_1p5x_at_anchor"], \
        "two-tier admitted concurrency under 1.5x device-only"
    assert ok["optimizer_loss_bitwise_identical"], \
        "optimizer offload changed the loss"
    assert ok["planner_refuses_unhidden_stash"], \
        "planner accepted a stash transfer that cannot hide"
    assert ok["planner_accepts_hidden_stash"], \
        "planner refused a stash transfer with roofline headroom"


if __name__ == "__main__":
    main()
