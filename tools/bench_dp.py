#!/usr/bin/env python
"""Data-parallel gradient-path A/B (ISSUE r8): allreduce vs reduce-scatter
vs quantized on the virtual device mesh, plus quantized-vs-fp32
convergence parity.

Produces BENCH_DP_r08.json. For each model config and reduce mode:

  - per-step latency, >=3 independent runs (fresh executor each), spreads;
  - collective_cost_ms_per_step = dp8 step minus the dp1-equivalent step
    (same per-device batch, no collectives) — the absolute per-step cost
    this host pays for the gradient exchange, the same reading
    tools/benchmark.py multiproc reports (a REAL multi-process world needs
    jaxlib >= 0.5; this container's 0.4.x CPU backend cannot form one, so
    the mesh is 8 single-process host devices and the caveat is stated);
  - grad_bytes_on_wire: analytic ring model AND the HLO census — the two
    must agree exactly (tests/test_zero_comm.py pins this balance).

Convergence: 100 steps, fixed seeds and feed stream, fp32-SPMD vs int8
(with and without error feedback) on the flagship-adjacent MLP and
stacked-LSTM configs; the artifact commits the sampled loss curves and
max |delta|.

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python tools/bench_dp.py | tee BENCH_DP_r08.json
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

if "XLA_FLAGS" not in os.environ:
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"

from probe_common import census_wire_bytes, collective_census  # noqa: E402

DP = 8
ITERS = 15
RUNS = 3
CONV_STEPS = 100


def _build(config):
    import paddle_tpu as pt
    from paddle_tpu import layers

    pt.reset_default_programs()
    pt.reset_global_scope()
    with pt.core.unique_name.guard():
        if config == "mlp":
            # comm-bound: 2.7 MB of gradients over ~0.4 MFLOP of compute
            x = layers.data("img", shape=[784])
            h = layers.fc(x, size=784, act="relu")
            logits = layers.fc(h, size=10)
            label = layers.data("label", shape=[1], dtype="int64")
            loss = layers.mean(layers.softmax_with_cross_entropy(
                logits, label))
        else:                                  # stacked_lstm
            from paddle_tpu.models import stacked_lstm
            loss = stacked_lstm.stacked_lstm_net(
                dict_dim=10000, emb_dim=256, hid_dim=256, max_len=32)[0]
        pt.optimizer.MomentumOptimizer(0.05, momentum=0.9).minimize(loss)
    return loss


def _feed(config, rng, bs):
    if config == "mlp":
        return {"img": rng.rand(bs, 784).astype("float32"),
                "label": rng.randint(0, 10, (bs, 1)).astype("int64")}
    seq = 32
    return {"words": rng.randint(0, 10000, (bs, seq)).astype("int64"),
            "words@SEQLEN": np.full((bs,), seq, dtype="int32"),
            "label": rng.randint(0, 2, (bs, 1)).astype("int64")}


def _strategy(mode, ef=False):
    from paddle_tpu.parallel.strategy import BuildStrategy, ReduceStrategy
    bst = BuildStrategy()
    bst.reduce_strategy = {"allreduce": ReduceStrategy.AllReduce,
                           "reduce_scatter": ReduceStrategy.ReduceScatter,
                           "quantized": ReduceStrategy.ReduceScatter,
                           }[mode]
    if mode == "quantized":
        bst.quant_comm = "int8"
        bst.comm_error_feedback = ef
    return bst


def _time_steps(run_step, iters=ITERS):
    out = run_step()
    float(np.asarray(out[0]).ravel()[0])           # compile + drain
    t0 = time.time()
    outs = [run_step() for _ in range(iters)]
    float(np.asarray(outs[-1]).ravel()[0])
    return (time.time() - t0) / iters * 1e3


def measure_mode(config, mode, bs):
    """One independent run: fresh program + executor. Returns
    (latency_ms, comm_fields or None)."""
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.parallel import ParallelExecutor, grad_comm

    loss = _build(config)
    exe = ParallelExecutor(loss_name=loss.name, build_strategy=_strategy(mode))
    pt.Executor().run(pt.default_startup_program())
    feed = _feed(config, np.random.RandomState(0), bs)
    lat = _time_steps(lambda: exe.run(feed=feed, fetch_list=[loss],
                                      return_numpy=False))
    prog, scope = pt.default_main_program(), pt.global_scope()
    rewritten = exe._prepare_program(prog, scope)
    analytic = (grad_comm.analytic_wire_bytes(rewritten, DP)
                or grad_comm.spmd_allreduce_wire_bytes(prog, DP))
    cs = list(exe._cache.values())[-1]
    hlo = cs.fn.lower(
        tuple(jnp.asarray(feed[n]) for n in cs.feed_names),
        tuple(scope.get(n) for n in cs.ro_names),
        tuple(scope.get(n) for n in cs.rw_names),
        np.uint32(0)).compile().as_text()
    census = collective_census(hlo)
    fields = {
        "grad_bytes_on_wire": analytic["grad_wire_bytes"],
        "param_allgather_bytes_on_wire":
            analytic["param_allgather_wire_bytes"],
        "wire_bytes_per_step_analytic": analytic["wire_bytes"],
        "wire_bytes_per_step_census": int(census_wire_bytes(
            census, DP, min_bytes=8)),
        "census_collectives": {k: len(v) for k, v in census.items()},
        "gradient_allreduce_instructions": sum(
            1 for b, _ in census.get("all-reduce", []) if b > 64),
    }
    return lat, fields


def measure_dp1(config, bs):
    """The no-collective yardstick: plain single-device executor on the
    per-shard batch (bs/DP) — identical per-device compute, zero comm."""
    import paddle_tpu as pt

    loss = _build(config)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    feed = _feed(config, np.random.RandomState(0), bs // DP)
    return _time_steps(lambda: exe.run(feed=feed, fetch_list=[loss],
                                       return_numpy=False))


def bench_config(config, bs):
    dp1 = [round(measure_dp1(config, bs), 3) for _ in range(RUNS)]
    row = {"config": config, "global_batch": bs, "dp": DP,
           "iters_per_run": ITERS, "runs": RUNS,
           "dp1_equiv_latency_ms": {"runs": dp1, "best": min(dp1)}}
    for mode in ("allreduce", "reduce_scatter", "quantized"):
        lats, fields = [], None
        for _ in range(RUNS):
            lat, fields = measure_mode(config, mode, bs)
            lats.append(round(lat, 3))
        row[mode] = {
            "latency_ms_runs": lats,
            "latency_ms": min(lats),
            "latency_ms_spread": [min(lats), max(lats)],
            "collective_cost_ms_per_step": round(min(lats) - min(dp1), 3),
            **fields,
        }
    ar, rs, q = (row[m] for m in ("allreduce", "reduce_scatter",
                                  "quantized"))
    row["grad_wire_reduction_rs_vs_allreduce"] = round(
        ar["grad_bytes_on_wire"] / rs["grad_bytes_on_wire"], 2)
    row["grad_wire_reduction_quant_vs_rs"] = round(
        rs["grad_bytes_on_wire"] / q["grad_bytes_on_wire"], 2)
    row["grad_wire_reduction_quant_vs_allreduce"] = round(
        ar["grad_bytes_on_wire"] / q["grad_bytes_on_wire"], 2)
    return row


def convergence(config, bs):
    """100 fixed-seed steps: fp32 SPMD vs int8 (+-error feedback)."""
    import paddle_tpu as pt
    from paddle_tpu.parallel import ParallelExecutor

    def run(mode, ef):
        loss = _build(config)
        exe = ParallelExecutor(loss_name=loss.name,
                               build_strategy=_strategy(mode, ef=ef))
        pt.Executor().run(pt.default_startup_program())
        losses = []
        for i in range(CONV_STEPS):
            feed = _feed(config, np.random.RandomState(10_000 + i), bs)
            losses.append(float(exe.run(feed=feed, fetch_list=[loss])[0]))
        return losses

    fp32 = run("allreduce", False)
    q_ef = run("quantized", True)
    q_raw = run("quantized", False)

    def delta(a):
        return float(max(abs(x - y) for x, y in zip(a, fp32)))

    sample = list(range(0, CONV_STEPS, 10)) + [CONV_STEPS - 1]
    return {
        "config": config, "steps": CONV_STEPS, "global_batch": bs,
        "seeds": "feed stream RandomState(10000+i); program seed 0",
        "loss_curve_sampled": {
            "step": sample,
            "fp32": [round(fp32[i], 5) for i in sample],
            "int8_error_feedback": [round(q_ef[i], 5) for i in sample],
            "int8_no_feedback": [round(q_raw[i], 5) for i in sample],
        },
        "final_loss": {"fp32": round(fp32[-1], 5),
                       "int8_error_feedback": round(q_ef[-1], 5),
                       "int8_no_feedback": round(q_raw[-1], 5)},
        "max_abs_delta_vs_fp32": {
            "int8_error_feedback": round(delta(q_ef), 5),
            "int8_no_feedback": round(delta(q_raw), 5)},
    }


def main():
    t0 = time.time()
    rows = [bench_config("mlp", 64), bench_config("stacked_lstm", 16)]
    conv = [convergence("mlp", 64), convergence("stacked_lstm", 16)]
    print(json.dumps({
        "bench": "data-parallel gradient path A/B (ISSUE r8)",
        "mesh": f"{DP} virtual CPU devices, single process "
                f"(jaxlib < 0.5: no multi-process CPU backend on this "
                f"container — tools/benchmark.py --update_method multiproc "
                f"carries the same reduce_mode/byte fields for hosts that "
                f"can form a real N-process world)",
        "rows": rows,
        "convergence": conv,
        "reading": {
            "grad_bytes_on_wire": "per device per step, ring model "
                "(probe_common.collective_wire_bytes). For the explicit "
                "modes (reduce_scatter/quantized) analytic == census to "
                "rounding (<= tens of bytes: per-instruction float "
                "(N-1)/N terms + the 4-byte scalar loss pmean) — WE emit "
                "those collectives; tests/test_zero_comm.py pins the "
                "balance exactly on the MLP. For SPMD allreduce the "
                "analytic row is the dense-gradient formula and XLA owns "
                "the instructions — it may restructure small collectives "
                "(0.04% delta on the LSTM row, committed side by side)",
            "collective_cost_ms_per_step": "mode latency minus the "
                "dp1-equivalent (same per-device batch, no collectives)",
        },
        "caveats": [
            "wall-clock on this mesh crosses a memcpy-speed interconnect "
            "shared by 8 host threads on 2 cores: byte fields are the "
            "TPU-transferable claim; ms fields are a this-host census "
            "(quantized mode trades wire bytes for quant/dequant compute, "
            "which a CPU mesh pays but free ICI does not reward)",
        ],
        "wall_s": round(time.time() - t0, 1),
    }, indent=1))


if __name__ == "__main__":
    main()
