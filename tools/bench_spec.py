"""Speculative-decoding load harness: the BENCH_SPEC artifact (ISSUE r22).

The amortization claim, measured end to end on the r20 traces: with a
γ=4 int8 draft over the paged engine, the SATURATED trace commits
>= 1.5x the tokens per TARGET forward of plain decode (each round pays
one γ+1-wide verify forward instead of γ+1 plain ticks of target weight
reads), with decode output TOKEN-IDENTICAL per request to the
target-only twin (greedy acceptance is structural, not statistical),
the block pool reconciling EXACTLY (used + free == n_blocks - 1,
refcounts balanced — checked after every speculative round via
PTPU_SPEC_POOL_CHECK) despite rejected-tail rollbacks, and the draft's
weights reconciling exactly through the r17 ledger identity
(params_draft predicted == hand-summed == measured).

Baselines: the r20 paged f32 engine (plain decode) on every trace, and
the r21 weight-quantized engine pair (quant="int8" with and without
speculation — the verify program rides the SAME resident payloads via
the quantize pass's twin-program path) on the saturated trace.

    JAX_PLATFORMS=cpu python tools/bench_spec.py           # full, writes
                                                  BENCH_SPEC_r22.json
    JAX_PLATFORMS=cpu python tools/bench_spec.py --smoke   # CI stanza
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# the r20 harness's exact pool/trace geometry: the saturated trace here
# IS the "saturated r20 trace" of the acceptance bar
_DIMS = dict(vocab=1000, d_model=64, d_inner=128, num_heads=4,
             num_layers=2)
_MAX_LEN = 64
_BLOCK_SIZE = 8
_PAGED_SLOTS = 16
_PAGED_BLOCKS = 4 * _MAX_LEN // _BLOCK_SIZE + 1      # +1 null
_GAMMA = 4


def _trace(rng, n_requests, mean_interarrival_s, mode):
    """The r20 trace generator (tools/bench_serve_kv.py), verbatim
    geometry: long-tail lengths, ~60% extending one of 3 shared
    16-token system prompts; poisson / bursty / saturated arrival
    shapes."""
    vocab = _DIMS["vocab"]
    prefixes = [rng.randint(0, vocab, 16).tolist() for _ in range(3)]
    out, t, i = [], 0.0, 0
    while i < n_requests:
        if mode == "bursty":
            t += float(rng.exponential(mean_interarrival_s * 5))
            fan = int(rng.randint(3, 7))
            pre = prefixes[rng.randint(len(prefixes))]
            group = [(pre, True)] * min(fan, n_requests - i)
        else:
            if mode == "poisson":
                t += float(rng.exponential(mean_interarrival_s))
            shared = bool(rng.rand() < 0.6)
            pre = prefixes[rng.randint(len(prefixes))] if shared else None
            group = [(pre, shared)]
        for j, (pre, shared) in enumerate(group):
            t_j = t + j * 0.02 if mode == "bursty" else t
            if shared:
                tail = rng.randint(0, vocab,
                                   int(rng.randint(2, 8))).tolist()
                prompt = list(pre) + tail
            else:
                plen = int(rng.choice([3, 4, 6, 8, 12, 20],
                                      p=[.2, .25, .2, .15, .1, .1]))
                prompt = rng.randint(0, vocab, plen).tolist()
            max_new = int(rng.choice([4, 6, 8, 16, 24],
                                     p=[.3, .25, .2, .15, .1]))
            max_new = min(max_new, _MAX_LEN - len(prompt))
            out.append((t_j, prompt, max_new))
            i += 1
    return out, prefixes


def _trainable_names(eng):
    return sorted(n for n, v in eng._program.current_block().vars.items()
                  if v.persistable and getattr(v, "trainable", False))


def _make_engine(scope, speculative=None, quant=None):
    from paddle_tpu.serving import PagedKVEngine, SpecConfig
    spec = None
    if speculative:
        spec = SpecConfig(gamma=_GAMMA, draft=speculative)
    return PagedKVEngine(n_slots=_PAGED_SLOTS, max_len=_MAX_LEN,
                         block_size=_BLOCK_SIZE, n_blocks=_PAGED_BLOCKS,
                         scope=scope, quant=quant, speculative=spec,
                         **_DIMS)


def _run_trace(eng, trace, prefixes):
    """Replay one arrival trace (feeder thread, real clock); returns
    (metrics row, per-request token streams in submission order)."""
    warm = [eng.submit([1], max_new=1)]
    warm += [eng.submit(list(p), max_new=1) for p in prefixes]
    eng.run_until_idle()
    assert all(r.done for r in warm)
    eng.n_ticks = eng.busy_slot_ticks = eng.total_slot_ticks = 0
    eng.tokens_out = 0
    eng.target_forwards = 0
    if eng.spec is not None:
        sp = eng.spec
        sp.rounds = sp.draft_ticks = sp.verify_forwards = 0
        sp.draft_proposed = sp.draft_accepted = 0
        sp.draft_s = sp.verify_s = 0.0

    order = []
    t0 = time.time()

    def feeder():
        for off, prompt, max_new in trace:
            delay = t0 + off - time.time()
            if delay > 0:
                time.sleep(delay)
            order.append(eng.submit(prompt, max_new))

    f = threading.Thread(target=feeder)
    f.start()
    done = []
    while f.is_alive() or eng.n_active or eng.n_pending:
        finished = eng.step()
        done.extend(finished)
        if not eng.n_active and not eng.n_pending:
            time.sleep(0.001)
    f.join()
    makespan = time.time() - t0
    eng.pager.pool.check()                # refcounts balance, exactly
    pool = eng.pager.pool
    row = {
        "n_requests": len(done),
        "tokens_out": int(eng.tokens_out),
        "target_forwards": int(eng.target_forwards),
        "tokens_per_target_forward": round(
            eng.tokens_out / max(eng.target_forwards, 1), 3),
        "tokens_per_sec": round(sum(len(r.tokens) for r in done)
                                / makespan, 1),
        "makespan_s": round(makespan, 3),
        "pool_reconciles": bool(pool.n_used + pool.n_free
                                == pool.n_blocks - 1),
    }
    if eng.spec is not None:
        s = eng.spec.stats()
        row["speculative"] = {
            "gamma": s["gamma"], "draft": s["draft"],
            "rounds": s["rounds"],
            "acceptance_rate": round(s["acceptance_rate"], 4),
            "draft_overhead": round(s["draft_overhead"], 4),
            "rolled_back_blocks": s["rolled_back_blocks"],
            "draft_param_bytes": s["draft_param_bytes"],
        }
    return row, [r.tokens for r in order]


def bench_draft_census(scope, make):
    """The draft-param ledger identity (r17 discipline, r22 category):
    params_draft predicted from the DRAFT program's declared shapes ==
    hand-summed resident draft_* arrays == measured state census."""
    from paddle_tpu.framework.costs import memory_categories
    from paddle_tpu.observability.memory import (per_device_bytes,
                                                 state_census)
    eng = make(speculative="int8")
    prog = eng.spec._draft_program
    pred = memory_categories(prog)
    names = [n for n, v in prog.current_block().vars.items()
             if v.persistable]
    meas = state_census(scope, prog, names)["categories"]
    hand = sum(int(per_device_bytes(scope.get(n)))
               for n in scope.local_var_names()
               if n.startswith("draft_"))
    pd_pred = int(pred.get("params_draft", 0))
    pd_meas = int(meas.get("params_draft", 0))
    return {
        "params_draft_predicted": pd_pred,
        "params_draft_hand_summed": hand,
        "params_draft_measured": pd_meas,
        "draft_param_bytes_engine": int(eng.spec.draft_param_bytes()),
        "ledger_identity_exact": pd_pred == hand == pd_meas
        == int(eng.spec.draft_param_bytes()),
    }


def bench(n_requests=48, mean_interarrival_s=0.002, smoke=False):
    import paddle_tpu as pt

    os.environ["PTPU_SPEC_POOL_CHECK"] = "1"   # check EVERY round
    if smoke:
        n_requests, mean_interarrival_s = 10, 0.001
    pt.reset_default_programs()
    pt.reset_global_scope()
    scope = pt.global_scope()          # all engines share one weight set
    rng = np.random.RandomState(20)    # the r20 seed: same traces
    runs = {}
    identical = True
    # a quantizing engine's pass ERASES the shared scope's f32 weights;
    # snapshot them off the first engine and restore before every later
    # construction so each engine quantizes/copies the SAME weight set
    # (the bench_qserve discipline)
    seed_eng = _make_engine(scope)
    f32_snap = {n: np.asarray(scope.get(n)).copy()
                for n in _trainable_names(seed_eng)}

    def make(speculative=None, quant=None):
        for n, a in f32_snap.items():
            scope.set_var(n, a)
        return _make_engine(scope, speculative=speculative, quant=quant)

    modes = [("saturated_overload", "saturated")] if smoke else [
        ("poisson_longtail", "poisson"),
        ("bursty_shared_prefix", "bursty"),
        ("saturated_overload", "saturated")]
    for tname, mode in modes:
        trace, prefixes = _trace(rng, n_requests, mean_interarrival_s,
                                 mode)
        plain_row, plain_tokens = _run_trace(make(), trace, prefixes)
        spec_row, spec_tokens = _run_trace(
            make(speculative="int8"), trace, prefixes)
        same = spec_tokens == plain_tokens
        identical = identical and same
        runs[tname] = {
            "plain_r20": plain_row, "speculative": spec_row,
            "decode_token_identical": bool(same),
            "tokens_per_target_forward_ratio": round(
                spec_row["tokens_per_target_forward"]
                / max(plain_row["tokens_per_target_forward"], 1e-9), 2),
            "tokens_per_sec_ratio": round(
                spec_row["tokens_per_sec"]
                / max(plain_row["tokens_per_sec"], 1e-9), 2),
        }

    # the r21 baseline pair: weight-quantized target, with and without
    # speculation (the verify program twin-shares the int8 payloads)
    trace, prefixes = _trace(rng, n_requests, mean_interarrival_s,
                             "saturated")
    q_plain_row, q_plain_tokens = _run_trace(
        make(quant="int8"), trace, prefixes)
    q_spec_row, q_spec_tokens = _run_trace(
        make(speculative="int8", quant="int8"), trace, prefixes)
    q_same = q_spec_tokens == q_plain_tokens
    runs["saturated_quant_target"] = {
        "plain_r21": q_plain_row, "speculative": q_spec_row,
        "decode_token_identical": bool(q_same),
        "tokens_per_target_forward_ratio": round(
            q_spec_row["tokens_per_target_forward"]
            / max(q_plain_row["tokens_per_target_forward"], 1e-9), 2),
    }

    census = bench_draft_census(scope, make)
    sat = runs["saturated_overload"]
    out = {
        "bench": "spec", "round": 22, "smoke": bool(smoke),
        "model": dict(_DIMS, max_len=_MAX_LEN),
        "pool": {"n_tick_slots": _PAGED_SLOTS, "block_size": _BLOCK_SIZE,
                 "n_blocks": _PAGED_BLOCKS},
        "gamma": _GAMMA,
        "n_requests_per_trace": n_requests,
        "runs": runs,
        "draft_census": census,
        "claims": {
            "decode_token_identical_all_traces": bool(identical and q_same),
            "spec_tokens_per_target_forward_ge_1p5x_at_saturation": bool(
                sat["tokens_per_target_forward_ratio"] >= 1.5),
            "acceptance_rate_measured": sat["speculative"]
            ["speculative"]["acceptance_rate"],
            "pool_reconciles_every_round": bool(all(
                r[k]["pool_reconciles"] for r in runs.values()
                for k in r if isinstance(r[k], dict))),
            "draft_census_ledger_exact": bool(
                census["ledger_identity_exact"]),
        },
        "notes": "CPU-mesh measured; the tokens-per-target-forward "
                 "ratio is architectural (accepted window positions per "
                 "verify forward), so it transfers to TPU — wall-clock "
                 "speedup additionally depends on the draft:target cost "
                 "ratio, which costs.speculative_expectation models. "
                 "Pool invariants are checked after EVERY speculative "
                 "round (PTPU_SPEC_POOL_CHECK=1), not just at drain.",
    }
    return out


def main():
    smoke = "--smoke" in sys.argv
    out = bench(smoke=smoke)
    doc = json.dumps(out, indent=1)
    print(doc, flush=True)
    if not smoke:
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        with open(os.path.join(repo, "BENCH_SPEC_r22.json"), "w") as f:
            f.write(doc + "\n")
    ok = out["claims"]
    assert ok["decode_token_identical_all_traces"], \
        "speculative decode diverged from the target-only twin"
    assert ok["pool_reconciles_every_round"], \
        "pool accounting did not reconcile"
    assert ok["draft_census_ledger_exact"], \
        "params_draft did not reconcile through the ledger identity"
    assert ok["spec_tokens_per_target_forward_ge_1p5x_at_saturation"], \
        "speculation did not amortize target forwards at saturation"


if __name__ == "__main__":
    main()
