"""Dispatch-gap census (ISSUE r7): decompose the blocked-vs-pipelined
overhang and collapse the roofline-cap byte interval.

Two unattributed numbers motivate this probe:

- bench.py:123-128 measured a flagship step at 194 ms blocked vs 101 ms
  pipelined — 93 ms of dispatch/fetch overhang never broken down
  (VERDICT r5 weak #2).
- PROBE_CAPS_r05's flagship byte interval [65.4, 76.9] GB (±8.1%) left
  the residual-to-cap question open: is XLA's bytes-accessed real
  traffic or double-charge?

Census A — DISPATCH: for each config, per-step wall measurements
(blocked = dispatch+execute+fetch round trip; pipelined = steady state,
realization only at the end; host_dispatch = time for the run call to
RETURN with the queue draining; fetch_wait = blocked minus the other
two) plus a jax.profiler trace pass whose `PjitFunction`/
`TfrtCpuExecutable::Execute` spans split the dispatch into jit argument
processing vs executable execution, and whose inter-`Execute` gaps are
the host-side analogue of the inter-kernel gap (this backend exposes no
per-kernel device timeline; on TPU the same pass reads per-fusion
events). The serving tick config additionally A/Bs Executor.run against
the r7 `Executor.prepare` fast path — the dispatch cost the serving
engine took off its tick.

Census B — BYTES: parse the compiled HLO's entry computation and charge
every instruction operands+outputs (probe_caps methodology), but split
the multi-consumer re-reads by buffer size: a buffer <= the VMEM budget
(16 MB) that several top-level instructions read is prefetched once and
re-read from VMEM (its recharge is NOT HBM traffic); a LARGER buffer
genuinely re-streams from HBM. The true-traffic interval is then
  [unique + large_recharges,  unique + all_recharges]
whose width is exactly the small-recharge mass — measured here <= ±5%,
the collapse PROBE_CAPS' upper-vs-lower reading needed.

    JAX_PLATFORMS=cpu python tools/probe_gap.py | tee PROBE_GAP_r07.json
"""

from __future__ import annotations

import json
import os
import re
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from probe_common import hlo_shape_bytes  # noqa: E402

_VMEM_BYTES = 16 << 20
_SKIP = {"get-tuple-element", "bitcast", "parameter", "tuple", "constant",
         "after-all", "copy-start", "async-start"}


# ---------------------------------------------------------------------------
# census B: byte-interval refinement
# ---------------------------------------------------------------------------

def refined_byte_census(hlo: str):
    """Entry-computation byte census with a LOCALITY-aware recharge
    split.

    Every top-level instruction charges operands+outputs (probe_caps
    methodology). A buffer's FIRST read and its write are always real
    traffic (`unique`). A RE-read is ambiguous — XLA's bytes-accessed
    charges it, the entry-census-minus-overlay reading doesn't — and the
    ambiguity is exactly PROBE_CAPS_r05's ±8% interval. The split that
    collapses it: a re-read is on-chip-resident (NOT fresh HBM traffic)
    only when (a) the buffer fits the 16 MB VMEM budget AND (b) less
    than a VMEM's worth of other traffic moved through since its last
    read (the schedule hasn't evicted it). Everything else re-streams.
    The residual interval
      [unique + far_recharges, unique + far + near_recharges]
    is then wide only by the near-recharge mass."""
    cur = None
    defs = {}            # name -> bytes
    last_read_at = {}    # name -> cumulative-bytes position of last read
    unique = near = far = overlay = 0
    cum = 0              # cumulative charged bytes = schedule position
    for line in hlo.splitlines():
        mc = re.match(r"(ENTRY )?%?([\w.\-]+)\s*\([^)]*\)\s*->", line)
        if mc:
            cur = "ENTRY" if mc.group(1) else mc.group(2)
            continue
        if cur != "ENTRY":
            continue
        m = re.match(r"\s+%?([\w.\-]+)\s*=\s*(\([^=]*?\)|\S+)\s+([a-z\-]+)",
                     line)
        if not m:
            continue
        name, sh, op = m.groups()
        out_b = hlo_shape_bytes(sh)
        defs[name] = out_b
        if op == "parameter":
            continue
        if op in ("copy-done", "async-done"):
            overlay += out_b
            continue
        if op in _SKIP:
            continue
        unique += out_b                      # the write
        cum += out_b
        call = line[m.end():]
        operands = re.findall(r"%([\w.\-]+)", call.split("metadata")[0])
        for o in dict.fromkeys(operands):
            if o not in defs:
                continue
            b = defs[o]
            seen = o in last_read_at
            if not seen:
                unique += b                  # first read: always real
            elif (b <= _VMEM_BYTES
                    and cum - last_read_at[o] <= _VMEM_BYTES):
                near += b                    # plausibly still resident
            else:
                far += b                     # re-streamed from HBM
            last_read_at[o] = cum
            cum += b
    low = unique + far
    high = unique + far + near
    mid = (low + high) / 2
    return {
        "unique_GB": round(unique / 1e9, 3),
        "recharge_far_GB": round(far / 1e9, 3),
        "recharge_near_GB": round(near / 1e9, 3),
        "prefetch_overlay_GB": round(overlay / 1e9, 3),
        "interval_GB": [round(low / 1e9, 3), round(high / 1e9, 3)],
        "interval_halfwidth_pct": round((high - low) / 2 / mid * 100, 2)
        if mid else 0.0,
    }


# ---------------------------------------------------------------------------
# census A: dispatch decomposition
# ---------------------------------------------------------------------------

def _realize(fetches):
    return float(np.asarray(fetches[0]).ravel()[0])


def _trace_spans(trace_dir):
    """(pjit spans, execute spans) in microseconds from a jax.profiler
    dump — PjitFunction = host dispatch incl. argument processing;
    TfrtCpuExecutable::Execute = the executable span."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from paddle_tpu.profiler import _collect_device_trace_events
    evs = [ev for ev in _collect_device_trace_events(trace_dir)
           if "ts" in ev and ev.get("dur", 0) > 0]
    pjit = [(ev["ts"], ev["dur"]) for ev in evs
            if str(ev.get("name", "")).startswith("PjitFunction")]
    execs = [(ev["ts"], ev["dur"]) for ev in evs
             if "Executable::Execute" in str(ev.get("name", ""))]
    # the profiler double-reports each span on nested planes: dedupe by
    # near-identical start time
    def dedupe(rows, eps=5.0):
        rows = sorted(rows)
        out = []
        for ts, dur in rows:
            if out and ts - out[-1][0] < eps:
                continue
            out.append((ts, dur))
        return out
    return dedupe(pjit), dedupe(execs)


def dispatch_census(name, run_fn, dispatch_fn, iters=6, windows=3,
                    trace_dir=None):
    """run_fn() -> fetches (full step); dispatch_fn() -> fetches with NO
    realization (the call-return time IS the host dispatch cost).

    Returns the blocked/pipelined/overhang decomposition with per-window
    spreads."""
    _realize(run_fn())                       # warm + drain

    blocked, dispatch, pipelined = [], [], []
    for _ in range(windows):
        t0 = time.time()
        _realize(run_fn())
        blocked.append((time.time() - t0) * 1e3)

        t0 = time.time()
        out = dispatch_fn()
        dispatch.append((time.time() - t0) * 1e3)
        _realize(out)                        # drain before next window

        t0 = time.time()
        outs = [run_fn() for _ in range(iters)]
        _realize(outs[-1])
        pipelined.append((time.time() - t0) / iters * 1e3)

    rec = {
        "config": name,
        "blocked_ms": round(min(blocked), 3),
        "blocked_ms_spread": [round(min(blocked), 3),
                              round(max(blocked), 3)],
        "pipelined_ms": round(min(pipelined), 3),
        "pipelined_ms_spread": [round(min(pipelined), 3),
                                round(max(pipelined), 3)],
        "host_dispatch_ms": round(min(dispatch), 3),
        "host_dispatch_ms_spread": [round(min(dispatch), 3),
                                    round(max(dispatch), 3)],
    }
    over = min(blocked) - min(pipelined)
    fetch_wait = max(over - min(dispatch), 0.0)
    rec["overhang_ms"] = round(over, 3)
    rec["overhang_decomposition"] = {
        "host_dispatch_ms": rec["host_dispatch_ms"],
        "fetch_wait_ms": round(fetch_wait, 3),
        "note": "overhang = blocked - pipelined; host_dispatch measured "
                "as the run call's return time on a drained queue; the "
                "rest of the overhang is fetch/transfer wait that "
                "pipelining hides",
    }

    if trace_dir is not None:
        import jax
        jax.profiler.start_trace(trace_dir)
        outs = [run_fn() for _ in range(iters)]
        _realize(outs[-1])
        jax.profiler.stop_trace()
        pjit, execs = _trace_spans(trace_dir)
        if len(execs) >= 2:
            exec_ms = float(np.mean([d for _, d in execs])) / 1e3
            pjit_ms = float(np.mean([d for _, d in pjit])) / 1e3 \
                if pjit else None
            gaps = [(execs[i + 1][0] - (execs[i][0] + execs[i][1])) / 1e3
                    for i in range(len(execs) - 1)]
            rec["trace_census"] = {
                "n_execute_spans": len(execs),
                "executable_execute_ms": round(exec_ms, 3),
                "pjit_dispatch_ms": round(pjit_ms, 3) if pjit_ms else None,
                "jit_arg_processing_ms": round(pjit_ms - exec_ms, 3)
                if pjit_ms else None,
                "inter_execute_gap_ms": round(float(np.mean(gaps)), 3),
                "gap_fraction_of_step": round(
                    float(np.mean(gaps))
                    / max(rec["pipelined_ms"], 1e-9), 3),
                "note": "spans from the jax.profiler trace: PjitFunction "
                        "= dispatch incl. jit argument processing, "
                        "Executable::Execute = the compiled program; the "
                        "inter-Execute gap is host-side time between "
                        "executions (Python executor + fetch handling) — "
                        "the per-kernel device gap needs the TPU trace, "
                        "this backend runs whole programs as one span",
            }
    return rec


# ---------------------------------------------------------------------------
# configs
# ---------------------------------------------------------------------------

def _build_lm(b, t):
    import paddle_tpu as pt
    from paddle_tpu.models import transformer

    pt.reset_default_programs()
    pt.reset_global_scope()
    rng = np.random.RandomState(0)
    with pt.core.unique_name.guard():
        loss, _ = transformer.transformer_lm(
            vocab=32000, max_len=t, d_model=512, d_inner=2048,
            num_heads=8, num_layers=6, dropout=0.0)
        pt.optimizer.AdamOptimizer(learning_rate=1e-4).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    import jax.numpy as jnp
    feed = {"tokens": jnp.asarray(rng.randint(0, 32000, (b, t))),
            "tokens@SEQLEN": jnp.asarray(np.full((b,), t, "int32")),
            "targets": jnp.asarray(rng.randint(0, 32000, (b, t)))}
    return exe, feed, loss


def _build_resnet(b):
    import paddle_tpu as pt
    from paddle_tpu import models

    pt.reset_default_programs()
    pt.reset_global_scope()
    rng = np.random.RandomState(0)
    with pt.core.unique_name.guard():
        loss, acc, _ = models.resnet.resnet_imagenet(
            depth=50, is_test=False, data_format="NHWC", use_bf16=True)
        pt.optimizer.MomentumOptimizer(learning_rate=3e-3,
                                       momentum=0.9).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    import jax.numpy as jnp
    feed = {"img": jnp.asarray(rng.rand(b, 224, 224, 3).astype("float32")),
            "label": jnp.asarray(rng.randint(0, 1000, (b, 1)))}
    return exe, feed, loss


def _hlo_for(exe, feed, loss):
    import paddle_tpu as pt
    compiled = exe._lookup_or_compile(pt.default_main_program(), dict(feed),
                                      [loss.name], pt.global_scope())
    import jax.numpy as jnp
    feed_vals = tuple(jnp.asarray(feed[n]) for n in compiled.feed_names)
    scope = pt.global_scope()
    ro = tuple(scope.get(n) for n in compiled.ro_names)
    rw = tuple(scope.get(n) for n in compiled.rw_names)
    ex = compiled.fn.lower(feed_vals, ro, rw, np.uint32(0)).compile()
    ca = ex.cost_analysis()
    ca = (ca[0] if isinstance(ca, (list, tuple)) else ca) or {}
    return ex.as_text(), float(ca.get("bytes accessed", 0.0))


def main():
    import jax

    on_accel = jax.devices()[0].platform != "cpu"
    lm_b, lm_t = (16, 512) if on_accel else (4, 128)
    rn_b = 64 if on_accel else 4

    # -- LM config (the PROBE_CAPS lm row's structure) --------------------
    big_iters, big_windows = (12, 3) if on_accel else (2, 2)
    exe, feed, loss = _build_lm(lm_b, lm_t)
    rec = dispatch_census(
        f"lm6l_512d_bs{lm_b}_T{lm_t}",
        lambda: exe.run(feed=feed, fetch_list=[loss], return_numpy=False),
        lambda: exe.run(feed=feed, fetch_list=[loss], return_numpy=False),
        iters=big_iters, windows=big_windows)
    hlo, xla_bytes = _hlo_for(exe, feed, loss)
    rec["byte_census"] = refined_byte_census(hlo)
    rec["byte_census"]["xla_bytes_accessed_GB"] = round(xla_bytes / 1e9, 3)
    print(json.dumps(rec), flush=True)

    # -- flagship structure (ResNet-50) -----------------------------------
    exe, feed, loss = _build_resnet(rn_b)
    rec = dispatch_census(
        f"resnet50_bs{rn_b}",
        lambda: exe.run(feed=feed, fetch_list=[loss], return_numpy=False),
        lambda: exe.run(feed=feed, fetch_list=[loss], return_numpy=False),
        iters=big_iters, windows=big_windows)
    hlo, xla_bytes = _hlo_for(exe, feed, loss)
    rec["byte_census"] = refined_byte_census(hlo)
    rec["byte_census"]["xla_bytes_accessed_GB"] = round(xla_bytes / 1e9, 3)
    print(json.dumps(rec), flush=True)

    # -- serving tick: Executor.run vs Executor.prepare dispatch ----------
    import paddle_tpu as pt
    from paddle_tpu.serving_engine import ContinuousBatchingEngine

    pt.reset_default_programs()
    pt.reset_global_scope()
    eng = ContinuousBatchingEngine(n_slots=8, vocab=1000, max_len=48,
                                   d_model=64, d_inner=128, num_heads=4,
                                   num_layers=2)
    tok = np.zeros((8, 1), np.int64)
    pos = np.zeros((8, 1, 1), np.float32)
    feed = {"tick_tok": tok, "tick_pos": pos}
    rec = dispatch_census(
        "serve_tick_lm2l_64d_8slots_prepared",
        lambda: eng._step.run(feed),
        lambda: eng._step.run(feed),
        iters=20, trace_dir="/tmp/probe_gap_tick")

    # prepared vs Executor.run, interleaved windows (ambient load drifts
    # faster than a sequential A-then-B measurement can tolerate)
    def _window(fn, iters=30):
        t0 = time.time()
        outs = [fn() for _ in range(iters)]
        _realize(outs[-1])
        return (time.time() - t0) / iters * 1e3

    def _prep():
        return eng._step.run(feed)

    def _full():
        return eng._exe.run(program=eng._program, feed=feed,
                            fetch_list=[eng._next_ids],
                            scope=eng.scope, return_numpy=False)

    _realize(_full())
    prep_ms = run_ms = None
    prep_all, run_all = [], []
    for _ in range(5):
        a = _window(_prep)
        b = _window(_full)
        prep_all.append(a)
        run_all.append(b)
        prep_ms = a if prep_ms is None else min(prep_ms, a)
        run_ms = b if run_ms is None else min(run_ms, b)
    rec["vs_executor_run"] = {
        "prepared_tick_ms": round(prep_ms, 3),
        "run_tick_ms": round(run_ms, 3),
        "prepared_tick_ms_per_window": [round(x, 3) for x in prep_all],
        "run_tick_ms_per_window": [round(x, 3) for x in run_all],
        "dispatch_saved_ms": round(run_ms - prep_ms, 3),
        "dispatch_saved_pct": round((run_ms - prep_ms) / run_ms * 100, 1),
    }
    print(json.dumps(rec), flush=True)

    print(json.dumps({
        "probe": "dispatch_gap_census", "round": 7,
        "device_kind": getattr(jax.devices()[0], "device_kind",
                               str(jax.devices()[0])),
        "caps_r05_flagship_interval_GB": [65.39, 76.91],
        "notes": "CPU-build measurement; the census METHOD (trace spans + "
                 "locality-aware recharge split) is what this round "
                 "commits, applied to this build's HLO and timeline. "
                 "BYTES: the interval's width is only the NEAR-recharge "
                 "mass (a <=16 MB buffer re-read before a VMEM's worth "
                 "of traffic passed is plausibly still resident; every "
                 "other re-read re-streams from HBM and moves to the "
                 "LOWER bound). The r05 [65.4, 76.9] flagship spread was "
                 "overlay + ALL recharges vs NONE; this split is what "
                 "collapses it, and on this build's HLO it lands "
                 "<= +/-5% (interval_halfwidth_pct per config). "
                 "DISPATCH: on this backend large-program dispatch is "
                 "effectively synchronous (blocked ~= pipelined; the "
                 "overhang and its spread are committed per config), so "
                 "the 93 ms bench.py:123-128 overhang is a TUNNEL "
                 "dispatch/fetch-latency property, not host work — the "
                 "tick-level census (serve_tick config) decomposes the "
                 "host share: jit-arg processing + executable span + "
                 "inter-execute gap, and the prepared-vs-run A/B prices "
                 "the executor's per-call bookkeeping directly.",
    }), flush=True)


if __name__ == "__main__":
    main()
