#!/usr/bin/env python
"""Benchmark CLI over the model zoo.

≙ reference benchmark/fluid/fluid_benchmark.py (models mnist / resnet / vgg /
stacked_dynamic_lstm / machine_translation with --update_method
{local,pserver,nccl2}, printing images/sec). TPU translation: the pserver and
nccl2 modes collapse into `--update_method collective` (ParallelExecutor over
the device mesh — compiled XLA collectives); `local` is the single-device
Executor. Synthetic data keeps the harness runnable anywhere
(≙ --use_fake_data).

Examples:
    python tools/benchmark.py --model resnet --batch_size 64 --iters 20
    python tools/benchmark.py --model transformer --update_method collective
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _mnist(args, rng):
    from paddle_tpu import layers
    from paddle_tpu.models import mnist
    loss, acc = mnist.mlp()[:2]
    feed = {"img": rng.rand(args.batch_size, 784).astype("float32"),
            "label": rng.randint(0, 10,
                                 (args.batch_size, 1)).astype("int64")}
    return loss, feed, args.batch_size


def _resnet(args, rng):
    from paddle_tpu.models import resnet
    loss, acc, _ = resnet.resnet_imagenet(
        depth=args.depth, data_format="NHWC", use_bf16=not args.no_bf16,
        class_num=1000)
    feed = {"img": rng.rand(args.batch_size, 224, 224, 3).astype("float32"),
            "label": rng.randint(0, 1000,
                                 (args.batch_size, 1)).astype("int64")}
    return loss, feed, args.batch_size


def _vgg(args, rng):
    from paddle_tpu.models import vgg
    loss, acc, _ = vgg.vgg(depth=16, class_num=1000,
                           image_shape=[224, 224, 3],
                           data_format="NHWC", use_bf16=not args.no_bf16)
    feed = {"img": rng.rand(args.batch_size, 224, 224, 3).astype("float32"),
            "label": rng.randint(0, 1000,
                                 (args.batch_size, 1)).astype("int64")}
    return loss, feed, args.batch_size


def _se_resnext(args, rng):
    from paddle_tpu import layers
    from paddle_tpu.models import se_resnext
    loss, acc, _ = se_resnext.se_resnext_imagenet(
        depth=50, use_bf16=not args.no_bf16)
    feed = {"img": rng.rand(args.batch_size, 224, 224, 3).astype("float32"),
            "label": rng.randint(0, 1000,
                                 (args.batch_size, 1)).astype("int64")}
    return loss, feed, args.batch_size


def _googlenet(args, rng):
    from paddle_tpu.models import googlenet
    loss, acc, _ = googlenet.googlenet_imagenet(use_bf16=not args.no_bf16)
    feed = {"img": rng.rand(args.batch_size, 224, 224, 3).astype("float32"),
            "label": rng.randint(0, 1000,
                                 (args.batch_size, 1)).astype("int64")}
    return loss, feed, args.batch_size


def _stacked_lstm(args, rng):
    import numpy as np
    from paddle_tpu.models import stacked_lstm
    seq = args.seq_len
    loss, acc, _ = stacked_lstm.stacked_lstm_net(
        dict_dim=10000, emb_dim=256, hid_dim=256, max_len=seq)
    feed = {"words": rng.randint(0, 10000,
                                 (args.batch_size, seq)).astype("int64"),
            "words@SEQLEN": np.full((args.batch_size,), seq, dtype="int32"),
            "label": rng.randint(0, 2,
                                 (args.batch_size, 1)).astype("int64")}
    return loss, feed, args.batch_size


def _machine_translation(args, rng):
    from paddle_tpu import layers
    from paddle_tpu.models import machine_translation as mt
    import numpy as np
    Ts = Tt = args.seq_len
    V = 10000
    src = layers.data("src", shape=[Ts], dtype="int64")
    src_lens = layers.data("src_lens", shape=[], dtype="int64")
    tgt_in = layers.data("tgt_in", shape=[Tt], dtype="int64")
    tgt_out = layers.data("tgt_out", shape=[Tt], dtype="int64")
    tgt_mask = layers.data("tgt_mask", shape=[Tt], dtype="float32")
    loss, _ = mt.train_net(src, src_lens, tgt_in, tgt_out, tgt_mask,
                           dict_size=V, embed_dim=256, hidden_dim=512)
    b = args.batch_size
    feed = {"src": rng.randint(2, V, (b, Ts)).astype("int64"),
            "src_lens": np.full((b,), Ts, "int64"),
            "tgt_in": rng.randint(2, V, (b, Tt)).astype("int64"),
            "tgt_out": rng.randint(2, V, (b, Tt)).astype("int64"),
            "tgt_mask": np.ones((b, Tt), "float32")}
    return loss, feed, b * Tt  # tokens/sec


def _transformer(args, rng):
    from paddle_tpu.models import transformer
    import numpy as np
    T = args.seq_len
    loss, _ = transformer.transformer_lm(
        vocab=32000, max_len=T, d_model=512, d_inner=2048, num_heads=8,
        num_layers=6, dropout=0.0)
    b = args.batch_size
    feed = {"tokens": rng.randint(0, 32000, (b, T)).astype("int64"),
            "tokens@SEQLEN": np.full((b,), T, "int32"),
            "targets": rng.randint(0, 32000, (b, T)).astype("int64")}
    return loss, feed, b * T  # tokens/sec


def _deepfm(args, rng):
    from paddle_tpu.models import deepfm
    import numpy as np
    b = args.batch_size
    loss, _ = deepfm.deepfm(num_fields=39, vocab_size=100000)
    feed = {"feat_ids": rng.randint(0, 100000, (b, 39)).astype("int64"),
            "feat_vals": rng.rand(b, 39).astype("float32"),
            "label": rng.randint(0, 2, (b, 1)).astype("float32")}
    return loss, feed, b


MODELS = {
    "mnist": _mnist,
    "resnet": _resnet,
    "vgg": _vgg,
    "se_resnext": _se_resnext,
    "googlenet": _googlenet,
    "stacked_lstm": _stacked_lstm,
    "machine_translation": _machine_translation,
    "transformer": _transformer,
    "deepfm": _deepfm,
}


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model", choices=sorted(MODELS), default="resnet")
    p.add_argument("--batch_size", type=int, default=32)
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument("--seq_len", type=int, default=64)
    p.add_argument("--depth", type=int, default=50)
    p.add_argument("--learning_rate", type=float, default=0.01)
    p.add_argument("--update_method", choices=["local", "collective"],
                   default="local",
                   help="local = single device; collective = "
                        "ParallelExecutor over the mesh (≙ nccl2/pserver)")
    p.add_argument("--optimizer", default="momentum",
                   choices=["sgd", "momentum", "adam"])
    p.add_argument("--no_bf16", action="store_true")
    p.add_argument("--profile", action="store_true")
    args = p.parse_args()
    if args.iters < 1:
        p.error("--iters must be >= 1")
    if args.warmup < 0:
        p.error("--warmup must be >= 0")

    import numpy as np
    import jax
    import paddle_tpu as pt

    rng = np.random.RandomState(0)
    loss, feed, units_per_step = MODELS[args.model](args, rng)

    opt = {"sgd": lambda: pt.optimizer.SGDOptimizer(args.learning_rate),
           "momentum": lambda: pt.optimizer.MomentumOptimizer(
               args.learning_rate, momentum=0.9),
           "adam": lambda: pt.optimizer.AdamOptimizer(args.learning_rate),
           }[args.optimizer]()
    opt.minimize(loss)

    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    if args.update_method == "collective":
        from paddle_tpu.parallel import ParallelExecutor
        runner = ParallelExecutor(loss_name=loss.name)
    else:
        runner = exe

    if args.profile:
        pt.profiler.start_profiler("All")
    out = None
    for _ in range(args.warmup):
        out = runner.run(feed=feed, fetch_list=[loss], return_numpy=False)
    if out is not None:
        jax.block_until_ready(out)

    t0 = time.time()
    for _ in range(args.iters):
        out = runner.run(feed=feed, fetch_list=[loss], return_numpy=False)
    jax.block_until_ready(out)
    dt = time.time() - t0
    if args.profile:
        pt.profiler.stop_profiler(sorted_key="total")

    unit = ("tokens/sec" if args.model in
            ("transformer", "machine_translation") else "examples/sec")
    print(json.dumps({
        "model": args.model,
        "update_method": args.update_method,
        "batch_size": args.batch_size,
        "iters": args.iters,
        "latency_ms": round(dt / args.iters * 1000, 3),
        "throughput": round(units_per_step * args.iters / dt, 2),
        "unit": unit,
        "device": jax.devices()[0].platform,
    }))


if __name__ == "__main__":
    main()
