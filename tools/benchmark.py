#!/usr/bin/env python
"""Benchmark CLI over the model zoo.

≙ reference benchmark/fluid/fluid_benchmark.py (models mnist / resnet / vgg /
stacked_dynamic_lstm / machine_translation with --update_method
{local,pserver,nccl2}, printing images/sec). TPU translation: the pserver and
nccl2 modes collapse into `--update_method collective` (ParallelExecutor over
the device mesh — compiled XLA collectives); `local` is the single-device
Executor; `multiproc` launches a REAL N-process jax.distributed world
(≙ the nccl2 multi-trainer path, fluid_benchmark.py:30-61) on this host's
virtual CPU mesh and reports per-process step time vs the single-process
collective baseline (the process-boundary overhead). Synthetic data keeps
the harness runnable anywhere (≙ --use_fake_data).

Examples:
    python tools/benchmark.py --model resnet --batch_size 64 --iters 20
    python tools/benchmark.py --model transformer --update_method collective
    python tools/benchmark.py --model mnist --update_method multiproc \
        --nproc 4 --local_devices 2 --iters 10
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import socket
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

if os.environ.get("PTPU_BENCH_CPU_BOOT"):
    # worker/baseline child of the multiproc driver: force the virtual CPU
    # platform BEFORE jax initializes (the axon TPU plugin would otherwise
    # pin jax_platforms to the tunnel)
    import jax._src.xla_bridge as _xb
    _xb._backend_factories.pop("axon", None)
    import jax
    jax.config.update("jax_platforms", "cpu")


def _mnist(args, rng):
    from paddle_tpu import layers
    from paddle_tpu.models import mnist
    loss, acc = mnist.mlp()[:2]
    feed = {"img": rng.rand(args.batch_size, 784).astype("float32"),
            "label": rng.randint(0, 10,
                                 (args.batch_size, 1)).astype("int64")}
    return loss, feed, args.batch_size


def _resnet(args, rng):
    from paddle_tpu.models import resnet
    loss, acc, _ = resnet.resnet_imagenet(
        depth=args.depth, data_format="NHWC", use_bf16=not args.no_bf16,
        class_num=1000)
    feed = {"img": rng.rand(args.batch_size, 224, 224, 3).astype("float32"),
            "label": rng.randint(0, 1000,
                                 (args.batch_size, 1)).astype("int64")}
    return loss, feed, args.batch_size


def _vgg(args, rng):
    from paddle_tpu.models import vgg
    loss, acc, _ = vgg.vgg(depth=16, class_num=1000,
                           image_shape=[224, 224, 3],
                           data_format="NHWC", use_bf16=not args.no_bf16)
    feed = {"img": rng.rand(args.batch_size, 224, 224, 3).astype("float32"),
            "label": rng.randint(0, 1000,
                                 (args.batch_size, 1)).astype("int64")}
    return loss, feed, args.batch_size


def _se_resnext(args, rng):
    from paddle_tpu import layers
    from paddle_tpu.models import se_resnext
    loss, acc, _ = se_resnext.se_resnext_imagenet(
        depth=50, use_bf16=not args.no_bf16)
    feed = {"img": rng.rand(args.batch_size, 224, 224, 3).astype("float32"),
            "label": rng.randint(0, 1000,
                                 (args.batch_size, 1)).astype("int64")}
    return loss, feed, args.batch_size


def _googlenet(args, rng):
    from paddle_tpu.models import googlenet
    loss, acc, _ = googlenet.googlenet_imagenet(use_bf16=not args.no_bf16)
    feed = {"img": rng.rand(args.batch_size, 224, 224, 3).astype("float32"),
            "label": rng.randint(0, 1000,
                                 (args.batch_size, 1)).astype("int64")}
    return loss, feed, args.batch_size


def _stacked_lstm(args, rng):
    import numpy as np
    from paddle_tpu.models import stacked_lstm
    seq = args.seq_len
    loss, acc, _ = stacked_lstm.stacked_lstm_net(
        dict_dim=10000, emb_dim=256, hid_dim=256, max_len=seq)
    feed = {"words": rng.randint(0, 10000,
                                 (args.batch_size, seq)).astype("int64"),
            "words@SEQLEN": np.full((args.batch_size,), seq, dtype="int32"),
            "label": rng.randint(0, 2,
                                 (args.batch_size, 1)).astype("int64")}
    return loss, feed, args.batch_size


def _machine_translation(args, rng):
    from paddle_tpu import layers
    from paddle_tpu.models import machine_translation as mt
    import numpy as np
    Ts = Tt = args.seq_len
    V = 10000
    src = layers.data("src", shape=[Ts], dtype="int64")
    src_lens = layers.data("src_lens", shape=[], dtype="int64")
    tgt_in = layers.data("tgt_in", shape=[Tt], dtype="int64")
    tgt_out = layers.data("tgt_out", shape=[Tt], dtype="int64")
    tgt_mask = layers.data("tgt_mask", shape=[Tt], dtype="float32")
    loss, _ = mt.train_net(src, src_lens, tgt_in, tgt_out, tgt_mask,
                           dict_size=V, embed_dim=256, hidden_dim=512)
    b = args.batch_size
    feed = {"src": rng.randint(2, V, (b, Ts)).astype("int64"),
            "src_lens": np.full((b,), Ts, "int64"),
            "tgt_in": rng.randint(2, V, (b, Tt)).astype("int64"),
            "tgt_out": rng.randint(2, V, (b, Tt)).astype("int64"),
            "tgt_mask": np.ones((b, Tt), "float32")}
    return loss, feed, b * Tt  # tokens/sec


def _transformer(args, rng):
    from paddle_tpu.models import transformer
    import numpy as np
    T = args.seq_len
    # mean_loss: identical math for the full-length feed below, and the
    # MEAN reduction form both manual modes (reduce_scatter, tp) require
    loss, _ = transformer.transformer_lm(
        vocab=32000, max_len=T, d_model=512, d_inner=2048, num_heads=8,
        num_layers=6, dropout=0.0, mean_loss=True)
    b = args.batch_size
    feed = {"tokens": rng.randint(0, 32000, (b, T)).astype("int64"),
            "tokens@SEQLEN": np.full((b,), T, "int32"),
            "targets": rng.randint(0, 32000, (b, T)).astype("int64")}
    return loss, feed, b * T  # tokens/sec


def _deepfm(args, rng):
    from paddle_tpu.models import deepfm
    import numpy as np
    b = args.batch_size
    loss, _ = deepfm.deepfm(num_fields=39, vocab_size=100000)
    feed = {"feat_ids": rng.randint(0, 100000, (b, 39)).astype("int64"),
            "feat_vals": rng.rand(b, 39).astype("float32"),
            "label": rng.randint(0, 2, (b, 1)).astype("float32")}
    return loss, feed, b


MODELS = {
    "mnist": _mnist,
    "resnet": _resnet,
    "vgg": _vgg,
    "se_resnext": _se_resnext,
    "googlenet": _googlenet,
    "stacked_lstm": _stacked_lstm,
    "machine_translation": _machine_translation,
    "transformer": _transformer,
    "deepfm": _deepfm,
}


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_child(args, extra_env, extra_args=()):
    """Re-exec this CLI as a child process on the virtual CPU platform.
    Output goes to temp FILES, not pipes: the parent polls without
    draining, and a pipe-buffered child (~64 KB of XLA/absl log spew)
    would deadlock in write() and read as a hang."""
    import tempfile
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("PADDLE_")}   # no stale world config leaks
    env["PTPU_BENCH_CPU_BOOT"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    env.update(extra_env)
    argv = [sys.executable, os.path.abspath(__file__),
            "--model", args.model, "--batch_size", str(args.batch_size),
            "--iters", str(args.iters), "--warmup", str(args.warmup),
            "--seq_len", str(args.seq_len), "--depth", str(args.depth),
            "--learning_rate", str(args.learning_rate),
            "--optimizer", args.optimizer,
            "--reduce_mode", args.reduce_mode,
            "--comm_bucket_bytes", str(args.comm_bucket_bytes),
            "--pipeline_stages", str(args.pipeline_stages),
            "--num_microbatches", str(args.num_microbatches),
            "--pipeline_schedule", args.pipeline_schedule] \
        + list(extra_args)
    if args.no_bf16:
        argv.append("--no_bf16")
    if args.comm_error_feedback:
        argv.append("--comm_error_feedback")
    if args.no_census:
        argv.append("--no_census")
    out_f = tempfile.TemporaryFile(mode="w+", prefix="ptpu_bench_out_")
    err_f = tempfile.TemporaryFile(mode="w+", prefix="ptpu_bench_err_")
    p = subprocess.Popen(argv, stdout=out_f, stderr=err_f, text=True,
                         env=env)
    p._ptpu_out, p._ptpu_err = out_f, err_f
    return p


def _child_output(p):
    out = err = ""
    for attr, var in (("_ptpu_out", "out"), ("_ptpu_err", "err")):
        f = getattr(p, attr, None)
        if f is not None:
            f.seek(0)
            text = f.read()
            f.close()
            if var == "out":
                out = text
            else:
                err = text
    return out, err


def _drive_quant_serving(args):
    """--quant_params: the weight-only quantized serving column family.

    Runs the continuous-batching decode engine twice on ONE weight set —
    f32 baseline, then quantized (framework/passes.py
    quantize_params_pass) — and prints one row per side with
    params_bytes before/after, the per-tick host-dispatch share from the
    engine's `ptpu_engine_dispatch_seconds` histogram (the zero-dispatch
    bound-tick path), and generated tokens/s. Greedy argmax on shared
    weights, so the token streams are also compared (int8 is typically
    token-identical; divergence is reported, not asserted — the serving
    tests pin the bound)."""
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu.serving import ContinuousBatchingEngine

    dims = dict(vocab=1000, max_len=64, d_model=64, d_inner=128,
                num_heads=4, num_layers=2)
    n_slots = max(2, min(args.batch_size, 8))
    pt.reset_default_programs()
    pt.reset_global_scope()
    scope = pt.global_scope()
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, dims["vocab"], 4).tolist()
               for _ in range(4 * n_slots)]
    rows, tokens = [], {}
    for quant in (None, args.quant_params):
        label = quant or "f32"
        eng = ContinuousBatchingEngine(n_slots=n_slots, scope=scope,
                                       cache_prefix=f"bq_{label}",
                                       quant=quant, **dims)
        warm = eng.submit([1], max_new=1)
        eng.run_until_idle()
        assert warm.done
        t0 = time.time()
        reqs = [eng.submit(list(p), max_new=16) for p in prompts]
        eng.run_until_idle()
        dt = time.time() - t0
        n_tok = sum(len(r.tokens) for r in reqs)
        tokens[label] = [r.tokens for r in reqs]
        rows.append({
            "engine": label,
            "params_bytes": (eng.params_bytes_quantized if eng.quant
                             else eng.params_bytes_f32),
            "quant_freed_bytes": eng.quant_freed_bytes,
            "dispatch_ms_p50": round(
                (eng._m_dispatch.quantile(0.5) or 0.0) * 1e3, 4),
            "tick_ms_p50": round(
                (eng._m_tick_latency.quantile(0.5) or 0.0) * 1e3, 4),
            "tokens_per_sec": round(n_tok / dt, 1),
        })
    import jax
    print(json.dumps({
        "model": "transformer_serving",
        "quant_params": args.quant_params,
        "batch_slots": n_slots,
        "params_bytes_before": rows[0]["params_bytes"],
        "params_bytes_after": rows[1]["params_bytes"],
        "params_ratio": round(rows[0]["params_bytes"]
                              / max(rows[1]["params_bytes"], 1), 3),
        "decode_token_identical": tokens["f32"]
            == tokens[args.quant_params],
        "rows": rows,
        "device": jax.devices()[0].platform,
    }))


def _drive_offload_serving(args):
    """--offload: the two-tier host-offload serving column family.

    Runs the paged decode engine twice at a deliberately tight device
    block pool on ONE weight set — device-only (head-of-line admission)
    vs two-tier (framework/offload.py host spill + prefetch) — and
    prints one row per side with admitted concurrency under backlog,
    tokens/s, the offload wire-byte columns, and the prefetch hit rate.
    Decode must stay token-identical across the pair and the wire
    census must reconcile EXACTLY (predicted = eviction/reload counters
    x per-block bytes vs the transfer stream's measured bytes) — both
    are asserted, same discipline as BENCH_OFFLOAD_r23.json."""
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu.framework import offload as _offload
    from paddle_tpu.serving import HostTierConfig, PagedKVEngine

    dims = dict(vocab=1000, max_len=64, d_model=64, d_inner=128,
                num_heads=4, num_layers=2)
    n_slots = max(2, min(args.batch_size, 16))
    pt.reset_default_programs()
    pt.reset_global_scope()
    scope = pt.global_scope()
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, dims["vocab"], 4).tolist()
               for _ in range(3 * n_slots)]
    tier = HostTierConfig(host_blocks=64, prefetch_distance=2,
                          rotate_quantum=8)
    rows, tokens = [], {}
    for label, host_tier in (("device_only", None), ("two_tier", tier)):
        _offload.reset_offload()
        eng = PagedKVEngine(n_slots=n_slots, block_size=8, n_blocks=13,
                            scope=scope, cache_prefix=f"bo_{label}",
                            host_tier=host_tier, **dims)
        warm = eng.submit([1], max_new=1)
        eng.run_until_idle()
        assert warm.done
        eng.ht_d2h_bytes = eng.ht_h2d_bytes = 0
        eng.pager.host_evictions = eng.pager.host_reloads = 0
        eng.pager.host_prefetch_hits = eng.pager.host_prefetch_misses = 0
        t0 = time.time()
        reqs = [eng.submit(list(p), max_new=16) for p in prompts]
        active = []
        while eng.n_active or eng.n_pending:
            backlogged = eng.n_pending > 0
            eng.step()
            if backlogged and eng.n_active:
                active.append(eng.n_active)
        dt = time.time() - t0
        tokens[label] = [list(r.tokens) for r in reqs]
        ht = eng.pager.stats()["host_tier"]
        per = eng._ht_per_block_bytes
        census_exact = True
        if host_tier is not None:
            eng.pager.check_two_tier()
            census_exact = (
                eng.ht_d2h_bytes == ht["host_evictions"] * per
                and eng.ht_h2d_bytes == ht["host_reloads"] * per)
        rows.append({
            "engine": label,
            "admitted_concurrency": round(
                float(np.mean(active)) if active else 0.0, 2),
            "tokens_per_sec": round(
                sum(len(r.tokens) for r in reqs) / dt, 1),
            "offload_d2h_bytes": int(eng.ht_d2h_bytes),
            "offload_h2d_bytes": int(eng.ht_h2d_bytes),
            "prefetch_hit_rate": round(
                ht["prefetch_hit_rate"], 3) if ht else 0.0,
            "census_exact": bool(census_exact),
        })
    identical = tokens["device_only"] == tokens["two_tier"]
    import jax
    print(json.dumps({
        "model": "transformer_serving_paged",
        "offload": True,
        "batch_slots": n_slots,
        "n_blocks": 13,
        "host_tier": {"host_blocks": tier.host_blocks,
                      "prefetch_distance": tier.prefetch_distance,
                      "rotate_quantum": tier.rotate_quantum},
        "decode_token_identical": bool(identical),
        "rows": rows,
        "device": jax.devices()[0].platform,
    }))
    assert identical, "two-tier decode diverged from device-only"
    assert all(r["census_exact"] for r in rows), \
        "offload wire census did not reconcile"


def _drive_multiproc(args):
    """Parent of the N-process world: spawn N trainer children + a
    1-process collective baseline on the same total device count, report
    the process-boundary overhead (≙ fluid_benchmark.py nccl2 launcher)."""
    total_dev = args.nproc * args.local_devices
    port = _free_port()
    trace_dir = args.trace_dir
    if trace_dir:
        os.makedirs(trace_dir, exist_ok=True)
    procs = []
    for rank in range(args.nproc):
        extra = {
            "PADDLE_TRAINING_ROLE": "TRAINER",
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(args.nproc),
            "PADDLE_COORDINATOR_ENDPOINT": f"127.0.0.1:{port}",
            "XLA_FLAGS":
                f"--xla_force_host_platform_device_count="
                f"{args.local_devices}",
        }
        worker_args = ["--update_method", "collective"]
        if trace_dir:
            worker_args += ["--trace_dir", trace_dir]
        procs.append(_spawn_child(args, extra, worker_args))
    ranks = {}
    try:
        # poll ALL ranks: a crashed rank must surface ITS stderr
        # immediately, not after a sibling's 900 s collective hang
        deadline = time.time() + 900
        pending = list(procs)
        while pending:
            for p in list(pending):
                if p.poll() is not None:
                    out, err = _child_output(p)
                    if p.returncode != 0:
                        raise RuntimeError(
                            f"worker failed (rc={p.returncode}):\n"
                            f"{err[-3000:]}")
                    rec = json.loads(out.strip().splitlines()[-1])
                    ranks[rec.get("rank", 0)] = rec
                    pending.remove(p)
            if pending:
                if time.time() > deadline:
                    raise RuntimeError(
                        f"{len(pending)} worker(s) still running at the "
                        f"900 s deadline")
                time.sleep(0.5)
    finally:
        # one failed/hung rank must not orphan siblings blocked in a
        # collective that will never complete
        for p in procs:
            if p.poll() is None:
                p.kill()

    base = _spawn_child(args, {
        "XLA_FLAGS":
            f"--xla_force_host_platform_device_count={total_dev}",
    }, ["--update_method", "collective"])
    try:
        base.wait(timeout=900)
    finally:
        # mirror the worker cleanup: a hung baseline must not stay
        # orphaned past the deadline, and its temp output files must be
        # closed (TemporaryFile unlinks on close) even on the raise path
        if base.poll() is None:
            base.kill()
            base.wait()
            _child_output(base)  # drain + close -> files reclaimed
            raise RuntimeError(
                "single-process baseline still running at the 900 s "
                "deadline; killed")
    out, err = _child_output(base)
    if base.returncode != 0:
        raise RuntimeError(f"baseline failed:\n{err[-3000:]}")
    baseline = json.loads(out.strip().splitlines()[-1])

    worst = max(r["latency_ms"] for r in ranks.values())
    overhead = (worst - baseline["latency_ms"]) / baseline["latency_ms"]
    # a tiny-compute config (mnist: ~10 ms/step) cannot amortize gloo
    # collective latency, and a 3000% "overhead" reads as a measurement
    # when it is a degeneracy (VERDICT r5 weak #5): below the threshold
    # the pct is suppressed and the ABSOLUTE per-step collective cost is
    # reported instead — that number IS interpretable (it is the
    # cross-process collective latency this host pays per step,
    # independent of how little compute hides under it)
    degenerate = baseline["latency_ms"] < 50.0
    collective_cost_ms = round(worst - baseline["latency_ms"], 3)
    merged_trace = None
    if trace_dir:
        import glob

        from paddle_tpu import profiler as prof
        paths = sorted(glob.glob(os.path.join(trace_dir,
                                              "trace_rank*.json")))
        if paths:
            merged_trace = prof.merge_process_traces(
                paths, os.path.join(trace_dir, "merged_trace.json"))
    # the per-rank comm fields are identical across ranks (same compiled
    # step); lift rank 0's into the aggregate row so multiproc rows stay
    # self-interpreting like the collective ones
    rank0 = ranks.get(0, {})
    comm_fields = {k: rank0[k] for k in
                   ("reduce_mode", "grad_bytes_on_wire",
                    "param_allgather_bytes_on_wire", "wire_bytes_per_step",
                    "wire_bytes_census", "census_collectives")
                   if k in rank0}
    print(json.dumps({
        "model": args.model,
        "update_method": "multiproc",
        "nproc": args.nproc,
        "local_devices_per_proc": args.local_devices,
        "total_devices": total_dev,
        "batch_size": args.batch_size,
        **comm_fields,
        "per_process_latency_ms": {str(k): v["latency_ms"]
                                   for k, v in sorted(ranks.items())},
        "worst_rank_latency_ms": worst,
        "single_process_latency_ms": baseline["latency_ms"],
        "multiproc_overhead_pct": (None if degenerate
                                   else round(overhead * 100, 1)),
        "collective_cost_ms_per_step": collective_cost_ms,
        "degenerate": degenerate,
        **({"degenerate_note":
            f"single-process step ({baseline['latency_ms']} ms) is too "
            f"small to amortize cross-process collectives; pct "
            f"suppressed — read collective_cost_ms_per_step "
            f"({collective_cost_ms} ms) as this host's per-step "
            f"collective latency census instead"} if degenerate else {}),
        "throughput": min(r["throughput"] for r in ranks.values()),
        "unit": baseline["unit"],
        "merged_trace": merged_trace,
    }))


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model", choices=sorted(MODELS), default="resnet")
    p.add_argument("--batch_size", type=int, default=32)
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument("--seq_len", type=int, default=64)
    p.add_argument("--depth", type=int, default=50)
    p.add_argument("--learning_rate", type=float, default=0.01)
    p.add_argument("--update_method",
                   choices=["local", "collective", "multiproc"],
                   default="local",
                   help="local = single device; collective = "
                        "ParallelExecutor over the mesh (≙ nccl2/pserver); "
                        "multiproc = N-process jax.distributed world on the "
                        "virtual CPU mesh (≙ nccl2 multi-trainer)")
    p.add_argument("--nproc", type=int, default=4,
                   help="multiproc: number of trainer processes")
    p.add_argument("--local_devices", type=int, default=2,
                   help="multiproc: virtual devices per process")
    p.add_argument("--optimizer", default="momentum",
                   choices=["sgd", "momentum", "adam"])
    p.add_argument("--reduce_mode", default="allreduce",
                   choices=["allreduce", "reduce_scatter", "quantized"],
                   help="gradient path for collective/multiproc runs: "
                        "allreduce = SPMD default; reduce_scatter = "
                        "explicit psum_scatter + sharded update + "
                        "all-gather; quantized = reduce_scatter with "
                        "int8 block-scaled transfers "
                        "(parallel/grad_comm.py)")
    p.add_argument("--comm_error_feedback", action="store_true",
                   help="per-replica error feedback for quantized mode")
    p.add_argument("--comm_bucket_bytes", type=int, default=-1,
                   help="gradient transfer bucket cap; -1 = strategy "
                        "default (4 MiB), 0 = one collective per gradient "
                        "(the probe_overlap A/B side)")
    p.add_argument("--pipeline_stages", type=int, default=0,
                   help="collective runs: pipeline-parallel stages K "
                        "(>= 2 cuts the op DAG over a pp mesh axis of "
                        "size K; the remaining devices form the dp axis). "
                        "0 = off (framework/passes.py "
                        "pipeline_partition_pass)")
    p.add_argument("--num_microbatches", type=int, default=4,
                   help="pipeline runs: microbatches M per step (batch "
                        "must divide by dp * M); bubble fraction is "
                        "(K-1)/(M+K-1)")
    p.add_argument("--pipeline_schedule", default="1f1b",
                   choices=["gpipe", "1f1b"],
                   help="pipeline runs: gpipe (all-fwd then all-bwd) or "
                        "1f1b (bounded activation stash)")
    p.add_argument("--tp", type=int, default=0,
                   help="collective runs: tensor-parallel degree T (>= 2 "
                        "adds a tp mesh axis, annotates the model with "
                        "the Megatron column/row/vocab recipe via "
                        "parallel.auto_shard.annotate_tp, and — in the "
                        "manual reduce_scatter/quantized modes — runs the "
                        "framework/sharding.py tp_shard_pass rewrite). "
                        "Composes with --pipeline_stages on a "
                        "dp x pp x tp mesh")
    p.add_argument("--auto", action="store_true",
                   help="let the auto-parallel planner "
                        "(framework/auto_parallel.py) choose the whole "
                        "strategy — mesh factorization over ALL visible "
                        "devices, reduce mode, quantized wire, buckets, "
                        "pipeline schedule/microbatches, memory plan — "
                        "instead of the flags below; forces "
                        "--update_method collective and emits "
                        "plan_predicted_ms / plan_rank / plan_search_s "
                        "columns. Mutually exclusive with --reduce_mode/"
                        "--pipeline_stages/--tp")
    p.add_argument("--no_census", action="store_true",
                   help="skip the HLO comm census fields (saves one AOT "
                        "compile on big models)")
    p.add_argument("--memory_plan", action="store_true",
                   help="also compile the memory-PLANNED twin "
                        "(framework/memory_plan.py, budget 2%% of the "
                        "measured step) and fill the "
                        "mem_planned_peak_bytes / mem_plan_reduction "
                        "columns from its MEASURED census (one extra "
                        "compile; needs the census, i.e. not "
                        "--no_census)")
    p.add_argument("--quant_params", choices=("int8", "int4"), default=None,
                   help="serving mode: run the continuous-batching decode "
                        "engine f32 vs weight-only-quantized "
                        "(quantize_params_pass) on one weight set and "
                        "print the quantized column family — params_bytes "
                        "before/after, per-tick dispatch_ms (the "
                        "zero-dispatch bound tick's host share), "
                        "tokens/s. Ignores the training flags")
    p.add_argument("--offload", action="store_true",
                   help="serving mode: run the paged decode engine at a "
                        "tight device block pool, device-only vs "
                        "two-tier host offload (framework/offload.py), "
                        "and print the offload column family — admitted "
                        "concurrency under backlog, tokens/s, "
                        "offload_{d2h,h2d}_bytes, prefetch_hit_rate. "
                        "Asserts token identity and the exact wire-byte "
                        "census. Ignores the training flags")
    p.add_argument("--no_bf16", action="store_true")
    p.add_argument("--profile", action="store_true")
    p.add_argument("--trace_dir", default=None,
                   help="write a per-rank Chrome trace here (multiproc "
                        "parent merges them into merged_trace.json)")
    args = p.parse_args()
    if args.iters < 1:
        p.error("--iters must be >= 1")
    if args.warmup < 0:
        p.error("--warmup must be >= 0")
    if args.auto:
        if (args.reduce_mode != "allreduce" or args.pipeline_stages
                or args.tp or args.update_method == "multiproc"):
            p.error("--auto owns the strategy; do not combine it with "
                    "--reduce_mode/--pipeline_stages/--tp/multiproc")
        args.update_method = "collective"

    if args.quant_params:
        _drive_quant_serving(args)
        return

    if args.offload:
        _drive_offload_serving(args)
        return

    if args.update_method == "multiproc":
        _drive_multiproc(args)
        return

    import numpy as np
    import jax
    import paddle_tpu as pt

    if args.no_bf16:
        # also flip the global matmul kill switch: builders that hardcode
        # use_bf16=True (transformer) honor --no_bf16 through it
        from paddle_tpu.core import flags as _flags
        _flags.set_flag("use_bf16_matmul", False)

    from paddle_tpu.distributed import init_parallel_env
    denv = init_parallel_env()  # no-op without PADDLE_COORDINATOR_ENDPOINT

    rng = np.random.RandomState(0)
    loss, feed, units_per_step = MODELS[args.model](args, rng)

    opt = {"sgd": lambda: pt.optimizer.SGDOptimizer(args.learning_rate),
           "momentum": lambda: pt.optimizer.MomentumOptimizer(
               args.learning_rate, momentum=0.9),
           "adam": lambda: pt.optimizer.AdamOptimizer(args.learning_rate),
           }[args.optimizer]()
    opt.minimize(loss)

    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    plan_fields = {}
    if args.auto:
        # planner-chosen strategy over every visible device: annotate tp
        # first (transformer-family models pick up the Megatron recipe;
        # models nothing matches keep tp pruned with a named reason),
        # then search from the default BuildStrategy base
        from paddle_tpu.framework import auto_parallel as _auto
        from paddle_tpu.parallel import ParallelExecutor, annotate_tp
        from paddle_tpu.parallel.mesh import DeviceMesh
        annotate_tp()
        plan_res = _auto.plan(pt.default_main_program(),
                              len(jax.devices()),
                              nominal_batch=args.batch_size)
        runner = ParallelExecutor(
            loss_name=loss.name, build_strategy=plan_res.strategy,
            mesh=DeviceMesh(jax.devices(), plan_res.mesh_axes))
        plan_fields = {
            "auto": True,
            "plan_point": plan_res.point.describe(),
            "plan_mesh_axes": dict(plan_res.mesh_axes),
            "plan_predicted_ms":
                round(plan_res.predicted_step_s * 1e3, 6),
            "plan_rank": plan_res.rank_of(plan_res.point),
            "plan_search_s": round(plan_res.search_s, 3),
            "plan_n_feasible": plan_res.n_feasible,
            "plan_rejections": dict(plan_res.rejections),
        }
    elif args.update_method == "collective":
        from paddle_tpu.parallel import ParallelExecutor
        from paddle_tpu.parallel.strategy import (BuildStrategy,
                                                  ReduceStrategy)
        bst = BuildStrategy()
        bst.reduce_strategy = {
            "allreduce": ReduceStrategy.AllReduce,
            "reduce_scatter": ReduceStrategy.ReduceScatter,
            "quantized": ReduceStrategy.ReduceScatter,
        }[args.reduce_mode]
        if args.reduce_mode == "quantized":
            bst.quant_comm = "int8"
        bst.comm_error_feedback = args.comm_error_feedback
        if args.comm_bucket_bytes >= 0:
            bst.comm_bucket_bytes = args.comm_bucket_bytes
        mesh = None
        t = max(args.tp, 1)
        if args.tp > 1:
            from paddle_tpu.parallel import annotate_tp
            annotated = annotate_tp()
            if not annotated:
                p.error(f"--tp {args.tp}: no parameter of model "
                        f"{args.model!r} matches the annotate_tp rules "
                        f"(transformer-family names)")
        if args.pipeline_stages > 1:
            from paddle_tpu.parallel.mesh import DeviceMesh
            bst.pipeline_stages = args.pipeline_stages
            bst.num_microbatches = args.num_microbatches
            bst.pipeline_schedule = args.pipeline_schedule
            devs = jax.devices()
            k = args.pipeline_stages
            if len(devs) % (k * t):
                p.error(f"--pipeline_stages {k} x --tp {t} must divide "
                        f"the device count {len(devs)}")
            axes = {"dp": len(devs) // (k * t), "pp": k}
            if t > 1:
                axes["tp"] = t
            mesh = DeviceMesh(devs, axes)
        elif t > 1:
            from paddle_tpu.parallel.mesh import DeviceMesh
            devs = jax.devices()
            if len(devs) % t:
                p.error(f"--tp {t} must divide the device count "
                        f"{len(devs)}")
            mesh = DeviceMesh(devs, {"dp": len(devs) // t, "tp": t})
        runner = ParallelExecutor(loss_name=loss.name, build_strategy=bst,
                                  mesh=mesh)
    else:
        runner = exe

    if args.profile:
        pt.profiler.start_profiler("All")
    out = None
    for _ in range(args.warmup):
        out = runner.run(feed=feed, fetch_list=[loss], return_numpy=False)
    if out is not None:
        jax.block_until_ready(out)

    trace_events = args.trace_dir is not None
    if trace_events:
        pt.profiler.reset_profiler()
        pt.profiler.start_profiler("All")
    # the timed window's spans come from the observability tracer — the
    # same executor/engine instrumentation every run records — instead of
    # per-tool perf_counter pairs; span_ms below is the per-step breakdown
    from paddle_tpu.observability import tracing as _tracing
    bench_mark = _tracing.mark()
    t0 = time.time()
    for i in range(args.iters):
        with _tracing.span("user", "bench/step", i=i):
            out = runner.run(feed=feed, fetch_list=[loss],
                             return_numpy=False)
    jax.block_until_ready(out)
    dt = time.time() - t0
    span_agg = _tracing.aggregate(_tracing.spans_since(bench_mark))
    span_ms = {name: round(row["total_ms"] / args.iters, 3)
               for name, row in sorted(span_agg.items())
               if name != "bench/step"}
    if args.profile:
        pt.profiler.stop_profiler(sorted_key="total")
    if trace_events:
        pt.profiler.export_chrome_tracing(os.path.join(
            args.trace_dir, f"trace_rank{denv.trainer_id}.json"))

    comm_fields = {}
    if args.update_method == "collective":
        # self-interpreting comm fields (≙ the r07 breadth rows carrying
        # bound_kind): which gradient path ran and what it puts on the
        # wire per device per step — analytic from the rewritten program's
        # comm plan, cross-checked by the HLO census when affordable
        # (the census == analytic balance is asserted exactly in
        # tests/test_zero_comm.py)
        from paddle_tpu.parallel import grad_comm as _gc
        from paddle_tpu.parallel.strategy import ReduceStrategy as _RS
        prog, scope = pt.default_main_program(), pt.global_scope()
        dp = runner._dp
        rewritten = runner._prepare_program(prog, scope)
        # same model selection as costs.predict: the SPMD ZeRO-1 mode
        # costs the sharded-update param all-gather on top of the grad
        # all-reduce (census-measured) — an allreduce-priced fallback
        # would under-report the --auto rows whenever the planner picks
        # reduce mode
        spmd_model = (_gc.spmd_zero1_wire_bytes
                      if runner.build_strategy.reduce_strategy == _RS.Reduce
                      else _gc.spmd_allreduce_wire_bytes)
        analytic = (_gc.analytic_wire_bytes(rewritten, dp)
                    or spmd_model(prog, dp))
        comm_fields = {
            "reduce_mode": (plan_fields["plan_point"] if args.auto
                            else args.reduce_mode),
            "total_devices": runner.device_count,
            "grad_bytes_on_wire": analytic["grad_wire_bytes"],
            "param_allgather_bytes_on_wire":
                analytic["param_allgather_wire_bytes"],
            "wire_bytes_per_step": analytic["wire_bytes"],
        }
        if args.tp > 1:
            # tp rows, same discipline as grad_bytes_on_wire: the
            # analytic per-device tp-collective bytes from the rewritten
            # program's spliced tp_* ops (framework/sharding.py ring
            # accounting, shared probe_common.collective_wire_bytes
            # model); None when the SPMD partitioner owns the tp
            # collectives (reduce_mode=allreduce)
            from paddle_tpu.framework.sharding import tp_analytic_wire_bytes
            tpw = tp_analytic_wire_bytes(rewritten, args.tp,
                                         nominal_batch=args.batch_size)
            comm_fields.update({
                "tp": args.tp,
                "tp_allreduce_bytes_on_wire":
                    tpw["tp_allreduce_wire_bytes"] if tpw else None,
                "tp_allgather_bytes_on_wire":
                    tpw["tp_allgather_wire_bytes"] if tpw else None,
                "tp_wire_bytes_per_step":
                    tpw["tp_wire_bytes"] if tpw else None,
                "tp_collective_counts":
                    tpw["tp_op_counts"] if tpw else None,
            })
        if args.pipeline_stages > 1:
            # same discipline as grad_bytes_on_wire: the analytic
            # boundary-transfer model (probe_common ring accounting /
            # collective-permute: one act + one grad buffer per tick),
            # and the exact schedule-table bubble fraction
            from paddle_tpu.parallel.pipeline import (
                pp_boundary_wire_bytes, schedule_census)
            sched_census = schedule_census(args.pipeline_schedule,
                                           args.num_microbatches,
                                           args.pipeline_stages)
            mb_rows = args.batch_size // max(
                1, dp * args.num_microbatches)
            wire = pp_boundary_wire_bytes(rewritten, mb_rows)
            comm_fields.update({
                "pipeline_stages": args.pipeline_stages,
                "num_microbatches": args.num_microbatches,
                "pipeline_schedule": args.pipeline_schedule,
                "bubble_fraction": sched_census["bubble_fraction"],
                "peak_stash_microbatches": sched_census["peak_stash"],
                "pp_boundary_bytes":
                    wire["pp_boundary_bytes"] if wire else None,
            })
        if not args.no_census:
            from probe_common import census_wire_bytes, collective_census
            cs = list(runner._cache.values())[-1]
            # one memoized AOT compile serves the wire census AND the
            # memory census below (Executor._aot_compiled)
            hlo = runner._aot_compiled(cs, feed, scope).as_text()
            census = collective_census(hlo)
            comm_fields["wire_bytes_census"] = int(census_wire_bytes(
                census, dp, min_bytes=8))
            comm_fields["census_collectives"] = {
                k: len(v) for k, v in census.items()}

    # memory + utilization columns (r17): the blocked-measured MFU (the
    # timed window above block_until_ready's, so dt is true step time)
    # and — unless --no_census — the measured memory census of the
    # executable the loop actually ran, next to the static prediction
    from paddle_tpu.framework import costs as _costs
    flops = _costs.program_flops_bytes(
        pt.default_main_program(), nominal_batch=args.batch_size)["flops"]
    ndev = max(1, int(getattr(runner, "device_count", 1)))
    mem_fields = {
        "model_flops_per_step": round(flops),
        "mfu": round(_costs.mfu(flops / ndev, dt / args.iters), 8),
    }
    if not args.no_census:
        census = runner.memory_census(feed=feed)
        pred_mem = _costs.predict(
            runner._prepare_program(pt.default_main_program(),
                                    pt.global_scope())
            if args.update_method == "collective"
            else pt.default_main_program(),
            dp=getattr(runner, "_dp", 1),
            nominal_batch=args.batch_size)["memory"]
        mem_fields.update({
            "mem_state_bytes": round(
                census["state"]["categories"]["state_total"]),
            "mem_temp_bytes": census["xla"]["temp_bytes"],
            "mem_temp_source": census["xla"]["temp_source"],
            "mem_peak_bytes": round(census["peak_bytes"]),
            "mem_predicted_peak_total_bytes":
                pred_mem["peak_total_bytes"],
            "mem_planned_peak_bytes": None,
            "mem_plan_reduction": None,
        })
    if args.memory_plan and not args.no_census:
        # the r18 planned twin: one extra compile of the memory-planned
        # program, censused with the same formula — the MEASURED
        # columns, not the prediction. The measured-step budget is
        # recorded on the plan (it gates candidates only under the
        # mandated-recompute mode; the default CSE-able plan is
        # time-safe by construction)
        from paddle_tpu.framework.passes import get_pass
        budget_s = 0.02 * dt / args.iters
        if args.update_method == "collective":
            import dataclasses
            bst2 = dataclasses.replace(
                runner.build_strategy, memory_plan=True,
                memory_plan_time_budget_s=budget_s)
            from paddle_tpu.parallel import ParallelExecutor
            twin = ParallelExecutor(loss_name=loss.name,
                                    build_strategy=bst2,
                                    mesh=runner.mesh)
            jax.block_until_ready(twin.run(feed=feed, fetch_list=[loss],
                                           return_numpy=False))
            census2 = twin.memory_census(feed=feed)
            planned_peak = census2["peak_bytes"]
        else:
            planned_prog = get_pass(
                "memory_plan_pass", nominal_batch=args.batch_size,
                time_budget_s=budget_s)(pt.default_main_program())
            twin = pt.Executor()
            jax.block_until_ready(twin.run(
                program=planned_prog, feed=feed, fetch_list=[loss],
                return_numpy=False))
            census2 = twin.memory_census(feed=feed,
                                         program=planned_prog)
            planned_peak = census2["peak_bytes"]
        mem_fields.update({
            "mem_planned_peak_bytes": round(planned_peak),
            "mem_plan_reduction": round(
                1.0 - planned_peak / max(census["peak_bytes"], 1.0), 4),
        })

    unit = ("tokens/sec" if args.model in
            ("transformer", "machine_translation") else "examples/sec")
    print(json.dumps({
        "model": args.model,
        "update_method": args.update_method,
        "rank": denv.trainer_id,
        "nproc": denv.num_trainers,
        "batch_size": args.batch_size,
        "iters": args.iters,
        "latency_ms": round(dt / args.iters * 1000, 3),
        "span_ms": span_ms,
        "throughput": round(units_per_step * args.iters / dt, 2),
        "unit": unit,
        "device": jax.devices()[0].platform,
        **mem_fields,
        **comm_fields,
        **plan_fields,
    }))


if __name__ == "__main__":
    main()
