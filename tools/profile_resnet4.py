"""Round-4 MFU attribution: materialized-buffer census of the optimized HLO.

Unlike profile_resnet3 (which counted every instruction line, including ones
living inside fusion bodies that never touch HBM), this parses computation
boundaries and counts ONLY top-level instructions of the entry / while-body
computations — the ones whose outputs are real buffers — bucketing output
bytes by opcode and dtype, and listing the biggest buffers with metadata.

    env PYTHONPATH=/root/.axon_site:/root/repo python tools/profile_resnet4.py
"""

from __future__ import annotations

import collections
import json
import re
import sys

import numpy as np


def shape_bytes(sh):
    it = {"bf16": 2, "f16": 2, "f32": 4, "s32": 4, "u32": 4, "s8": 1,
          "u8": 1, "pred": 1, "s64": 8, "u64": 8}
    total = 0
    for m in re.finditer(r"(bf16|f32|f16|s32|u32|s8|u8|pred|s64|u64)"
                         r"\[([0-9,]*)\]", sh):
        n = 1
        for d in m.group(2).split(","):
            if d:
                n *= int(d)
        total += n * it[m.group(1)]
    return total


def main():
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu import models

    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 256
    pt.reset_default_programs()
    pt.reset_global_scope()
    loss, acc, _ = models.resnet.resnet_imagenet(
        depth=50, is_test=False, data_format="NHWC", use_bf16=True)
    opt = pt.optimizer.MomentumOptimizer(learning_rate=3e-3, momentum=0.9)
    opt.minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())

    rng = np.random.RandomState(0)
    feed = {
        "img": jnp.asarray(rng.rand(batch, 224, 224, 3).astype("float32")),
        "label": jnp.asarray(rng.randint(0, 1000, (batch, 1)).astype("int64")),
    }
    compiled = exe._lookup_or_compile(
        pt.default_main_program(), feed, [loss.name], pt.global_scope())
    feed_vals = tuple(jnp.asarray(feed[n]) for n in compiled.feed_names)
    scope = pt.global_scope()
    ro_vals = tuple(scope.get(n) for n in compiled.ro_names)
    rw_vals = tuple(scope.get(n) for n in compiled.rw_names)
    ex = compiled.fn.lower(feed_vals, ro_vals, rw_vals,
                           np.uint32(0)).compile()
    hlo = ex.as_text()
    with open("/tmp/resnet_train_optimized.hlo", "w") as f:
        f.write(hlo)

    # walk computations; keep only instructions in the entry computation
    # (jit program top level = the materialized buffers)
    cur_comp = None
    entry_ops = []
    for line in hlo.splitlines():
        mc = re.match(r"(ENTRY )?%?([\w.\-]+)\s*\([^)]*\)\s*->", line)
        if mc:
            cur_comp = ("ENTRY" if mc.group(1) else mc.group(2))
            continue
        if cur_comp != "ENTRY":
            continue
        m = re.match(r"\s+%?([\w.\-]+)\s*=\s*(\S+)\s+([a-z\-]+)", line)
        if not m:
            continue
        name, sh, op = m.groups()
        b = shape_bytes(sh)
        meta = ""
        mm = re.search(r'op_name="([^"]*)"', line)
        if mm:
            meta = mm.group(1)
        entry_ops.append((b, op, sh, name, meta))

    op_bytes = collections.Counter()
    op_count = collections.Counter()
    dtype_bytes = collections.Counter()
    for b, op, sh, name, meta in entry_ops:
        op_bytes[op] += b
        op_count[op] += 1
        md = re.match(r"(bf16|f32|f16|s32|u32|s8|u8|pred|s64|u64)", sh)
        if md:
            dtype_bytes[md.group(1)] += b
    print(json.dumps({
        "exp": "entry_output_bytes_by_op",
        "total_GB": round(sum(op_bytes.values()) / 1e9, 2),
        "top": [(op, round(bb / 1e9, 2), op_count[op])
                for op, bb in op_bytes.most_common(18)],
        "by_dtype_GB": {d: round(bb / 1e9, 2)
                        for d, bb in dtype_bytes.most_common()},
    }), flush=True)
    big = sorted(entry_ops, reverse=True)[:20]
    print(json.dumps({
        "exp": "biggest_entry_buffers",
        "top20": [(round(b / 1e6), op, sh[:48], meta[:90])
                  for b, op, sh, name, meta in big],
    }), flush=True)
    ca = ex.cost_analysis()
    ca = ca[0] if isinstance(ca, (list, tuple)) else ca
    print(json.dumps({
        "exp": "cost_analysis",
        "bytes_accessed_GB": round(float(ca.get("bytes accessed", 0)) / 1e9,
                                   2),
        "flops_G": round(float(ca.get("flops", 0)) / 1e9, 1),
    }), flush=True)


if __name__ == "__main__":
    main()
