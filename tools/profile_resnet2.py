"""Round-3 MFU attribution, part 2: roofline + phase split.

    env PYTHONPATH=/root/.axon_site:/root/repo python tools/profile_resnet2.py

Experiments:
  roofline      XLA cost-analysis bytes-accessed of the full train step ->
                HBM-bound vs MXU-bound verdict at 819 GB/s / 197 TFLOP/s
  fwd_only      forward+loss only (no grads/update): is the bwd pass
                disproportionately slow?
  stem_conv     conv1 (7x7/s2 over C=3) fwd+bwd alone: the known
                MXU-hostile layer, candidate for space-to-depth
  body_conv     a representative 3x3 bottleneck conv (C=128, 28x28):
                what efficiency does the MXU-friendly bulk reach?
"""

from __future__ import annotations

import json
import time

import numpy as np

HBM_GBPS = 819.0     # v5e spec
PEAK_TFLOPS = 197.0  # v5e bf16


def _realize(x):
    return float(np.asarray(x).ravel()[0])


def _timed(fn, *args, iters=10):
    out = fn(*args)
    _realize(out[0] if isinstance(out, (tuple, list)) else out)
    t0 = time.time()
    for _ in range(iters):
        out = fn(*args)
    _realize(out[0] if isinstance(out, (tuple, list)) else out)
    return (time.time() - t0) / iters


def roofline_and_fwd(batch=256):
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu import models

    pt.reset_default_programs()
    pt.reset_global_scope()
    loss, acc, _ = models.resnet.resnet_imagenet(
        depth=50, is_test=False, data_format="NHWC", use_bf16=True)
    opt = pt.optimizer.MomentumOptimizer(learning_rate=3e-3, momentum=0.9)
    opt.minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())

    rng = np.random.RandomState(0)
    feed = {
        "img": jnp.asarray(rng.rand(batch, 224, 224, 3).astype("float32")),
        "label": jnp.asarray(rng.randint(0, 1000, (batch, 1)).astype("int64")),
    }
    ca = exe.cost_analysis(feed=feed, fetch_list=[loss])
    flops = float(ca.get("flops", 0.0))
    baw = float(ca.get("bytes accessed", 0.0))
    out_b = float(ca.get("bytes accessed output", 0.0))
    t_flops_ms = flops / (PEAK_TFLOPS * 1e12) * 1e3
    t_hbm_ms = baw / (HBM_GBPS * 1e9) * 1e3
    print(json.dumps({
        "exp": "roofline_train_step", "flops": flops,
        "bytes_accessed": baw, "bytes_accessed_output": out_b,
        "ideal_compute_ms": round(t_flops_ms, 1),
        "ideal_hbm_ms": round(t_hbm_ms, 1),
        "arithmetic_intensity": round(flops / max(baw, 1), 1),
    }), flush=True)

    # fwd only: fresh program without backward/update
    pt.reset_default_programs()
    pt.reset_global_scope()
    loss2, acc2, _ = models.resnet.resnet_imagenet(
        depth=50, is_test=False, data_format="NHWC", use_bf16=True)
    exe2 = pt.Executor()
    exe2.run(pt.default_startup_program())
    dt = _timed(lambda: exe2.run(feed=feed, fetch_list=[loss2],
                                 return_numpy=False)[0])
    ca2 = exe2.cost_analysis(feed=feed, fetch_list=[loss2])
    f2 = float(ca2.get("flops", 0.0))
    print(json.dumps({
        "exp": "fwd_only_bs256", "step_ms": round(dt * 1e3, 2),
        "flops": f2,
        "implied_tflops": round(f2 / dt / 1e12, 1),
    }), flush=True)


def conv_micro(name, x_shape, k_shape, stride, padding):
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(*x_shape).astype(np.float32),
                    dtype=jnp.bfloat16)
    k = jnp.asarray(rng.randn(*k_shape).astype(np.float32),
                    dtype=jnp.bfloat16)

    def f(x, k):
        out = jax.lax.conv_general_dilated(
            x, k, (stride, stride), padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        return jnp.sum(out.astype(jnp.float32))

    g = jax.jit(jax.grad(f, argnums=(0, 1)))
    dt = _timed(lambda: g(x, k)[0][0, 0, 0, 0])
    n, h, w, _ = x_shape
    kh, kw, ci, co = k_shape
    oh = (h + sum(padding[0]) - kh) // stride + 1
    ow = (w + sum(padding[1]) - kw) // stride + 1
    flops = 3 * 2 * n * oh * ow * kh * kw * ci * co  # fwd+2 bwd convs
    print(json.dumps({
        "exp": name, "ms": round(dt * 1e3, 2),
        "tflops_attained": round(flops / dt / 1e12, 1),
        "pct_peak": round(flops / dt / 1e12 / PEAK_TFLOPS * 100, 1),
    }), flush=True)


def main():
    import jax
    print(json.dumps({"devices": [str(d) for d in jax.devices()]}),
          flush=True)
    roofline_and_fwd()
    conv_micro("stem_conv7x7s2_c3", (256, 224, 224, 3), (7, 7, 3, 64), 2,
               ((3, 3), (3, 3)))
    conv_micro("stem_s2d_conv4x4s1_c12", (256, 112, 112, 12),
               (4, 4, 12, 64), 1, ((1, 2), (1, 2)))
    conv_micro("body_conv3x3_c128", (256, 28, 28, 128), (3, 3, 128, 128), 1,
               ((1, 1), (1, 1)))
    conv_micro("body_conv3x3_c256_14", (256, 14, 14, 256),
               (3, 3, 256, 256), 1, ((1, 1), (1, 1)))


if __name__ == "__main__":
    main()
