"""Micro-probe: strategies for coalescing duplicate sparse-grad rows on TPU.

160k int32 ids in [0, 1M) with [160k, 128] f32 values (the DeepFM config's
merged-grad shape). Compares:
  a) unique + dup-index scatter-add  (current _merge_sparse_rows)
  b) argsort + run-boundary segment ids + SORTED scatter-add
  c) argsort + cumsum-diff (no scatter at all: gathers only)

    env PYTHONPATH=/root/.axon_site:/root/repo python tools/probe_merge.py
"""
import json
import time

import numpy as np


def main(n=159744, vocab=1000000, width=128):
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, vocab, (n,)).astype(np.int32))
    vals = jnp.asarray(rng.rand(n, width).astype(np.float32))

    def merge_unique(ids, vals):
        rows_u, inv = jnp.unique(ids, return_inverse=True, size=n,
                                 fill_value=vocab)
        out = jnp.zeros((n, width), jnp.float32).at[inv.reshape(-1)].add(vals)
        return rows_u, out

    def merge_sorted_scatter(ids, vals):
        perm = jnp.argsort(ids)
        sid = ids[perm]
        sval = vals.at[perm].get(unique_indices=True)
        new = jnp.concatenate([jnp.ones((1,), bool), sid[1:] != sid[:-1]])
        seg = jnp.cumsum(new) - 1                      # sorted, dup
        out = jnp.zeros((n, width), jnp.float32).at[seg].add(
            sval, indices_are_sorted=True)
        rows_u = jnp.full((n,), vocab, jnp.int32).at[seg].set(
            sid, indices_are_sorted=True)
        return rows_u, out

    def merge_cumsum(ids, vals):
        perm = jnp.argsort(ids)
        sid = ids[perm]
        sval = vals.at[perm].get(unique_indices=True)
        csum = jnp.cumsum(sval, axis=0)
        last = jnp.concatenate([sid[1:] != sid[:-1],
                                jnp.ones((1,), bool)])   # run ends
        new = jnp.concatenate([jnp.ones((1,), bool), sid[1:] != sid[:-1]])
        seg = jnp.cumsum(new) - 1
        # position of each run's END in sorted order, compacted to the front
        end_pos = jnp.full((n,), n - 1, jnp.int32).at[
            jnp.where(last, seg, n - 1)].max(jnp.arange(n, dtype=jnp.int32))
        runs = csum.at[end_pos].get(indices_are_sorted=True)
        prev = jnp.where((jnp.arange(n) == 0)[:, None], 0.0,
                         csum.at[jnp.clip(end_pos - 1, 0, n - 1)].get())
        # prev run's end cumsum: for run u>0 it's csum[end_pos[u-1]]
        prev_end = jnp.concatenate([jnp.full((1,), -1, jnp.int32),
                                    end_pos[:-1]])
        prevc = jnp.where((prev_end < 0)[:, None], 0.0,
                          csum.at[jnp.clip(prev_end, 0, n - 1)].get())
        out = runs - prevc
        rows_u = jnp.full((n,), vocab, jnp.int32).at[seg].set(
            sid, indices_are_sorted=True)
        return rows_u, out

    def merge_segscan(ids, vals):
        """Segmented inclusive scan over SORTED rows (Hillis-Steele shift
        adds) — no scatter anywhere, so nothing serializes per-index."""
        perm = jnp.argsort(ids)
        sid = ids[perm]
        sval = vals.at[perm].get(unique_indices=True)
        flag = jnp.concatenate([jnp.ones((1,), bool), sid[1:] != sid[:-1]])
        acc = sval
        f = flag
        off = 1
        while off < n:
            sh_acc = jnp.concatenate([jnp.zeros((off, width), acc.dtype),
                                      acc[:-off]])
            sh_f = jnp.concatenate([jnp.ones((off,), bool), f[:-off]])
            acc = jnp.where(f[:, None], acc, acc + sh_acc)
            f = f | sh_f
            off *= 2
        last = jnp.concatenate([sid[1:] != sid[:-1],
                                jnp.ones((1,), bool)])
        end_pos, = jnp.nonzero(last, size=n, fill_value=n - 1)
        nu = jnp.sum(last)
        valid = jnp.arange(n) < nu
        rows_u = jnp.where(valid, sid[end_pos],
                           vocab + jnp.arange(n, dtype=sid.dtype))
        vals_u = acc.at[end_pos].get(indices_are_sorted=True)
        return rows_u, vals_u

    def argsort_only(ids, vals):
        perm = jnp.argsort(ids)
        return ids[perm], vals.at[perm].get(unique_indices=True)

    def unique_only(ids, vals):
        rows_u, inv = jnp.unique(ids, return_inverse=True, size=n,
                                 fill_value=vocab)
        return rows_u, vals

    ref_r, ref_v = jax.jit(merge_unique)(ids, vals)
    for name, fn in (("unique_scatter", merge_unique),
                     ("sorted_scatter", merge_sorted_scatter),
                     ("cumsum_diff", merge_cumsum),
                     ("segscan", merge_segscan),
                     ("argsort_only", argsort_only),
                     ("unique_only", unique_only)):
        f = jax.jit(fn)
        try:
            r, v = f(ids, vals)
            float(jnp.asarray(v).ravel()[0])
        except Exception as e:
            print(json.dumps({"name": name, "err": f"{e!s:.100}"}),
                  flush=True)
            continue
        # correctness vs reference (compare sum over all rows + spot rows)
        ok = bool(jnp.allclose(jnp.sort(jnp.asarray(r)),
                               jnp.sort(jnp.asarray(ref_r))))
        okv = bool(jnp.allclose(v.sum(), ref_v.sum(), rtol=1e-4))
        best = None
        for _ in range(3):
            t0 = time.time()
            for _ in range(10):
                r, v = f(ids, vals)
            float(jnp.asarray(v).ravel()[0])
            dt = (time.time() - t0) / 10
            best = dt if best is None else min(best, dt)
        print(json.dumps({"name": name, "ms": round(best * 1e3, 2),
                          "rows_ok": ok, "vals_ok": okv}), flush=True)


if __name__ == "__main__":
    main()
