#!/usr/bin/env python
"""Print the public API surface as stable one-line signatures.

≙ reference tools/print_signatures.py + paddle/fluid/API.spec +
tools/diff_api.py: the public Python surface is frozen in a golden file and
CI fails on unreviewed changes. Run with --update to regenerate API.spec.

Usage:
    python tools/print_signatures.py            # print to stdout
    python tools/print_signatures.py --update   # rewrite API.spec
"""

from __future__ import annotations

import inspect
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# modules whose public (non-underscore) callables/classes form the API
PUBLIC_MODULES = [
    "paddle_tpu",
    "paddle_tpu.layers",
    "paddle_tpu.layers.control_flow",
    "paddle_tpu.layers.sequence",
    "paddle_tpu.layers.io",
    "paddle_tpu.layers.detection",
    "paddle_tpu.layers.learning_rate_scheduler",
    "paddle_tpu.optimizer",
    "paddle_tpu.initializer",
    "paddle_tpu.regularizer",
    "paddle_tpu.clip",
    "paddle_tpu.metrics",
    "paddle_tpu.average",
    "paddle_tpu.evaluator",
    "paddle_tpu.io",
    "paddle_tpu.profiler",
    "paddle_tpu.trainer",
    "paddle_tpu.inferencer",
    "paddle_tpu.serving",
    "paddle_tpu.serving.kv_pager",
    "paddle_tpu.serving.sanitizer",
    "paddle_tpu.serving_engine",
    "paddle_tpu.nets",
    "paddle_tpu.concurrency",
    "paddle_tpu.transpiler",
    "paddle_tpu.distributed",
    "paddle_tpu.framework.analysis",
    "paddle_tpu.framework.auto_parallel",
    "paddle_tpu.framework.costs",
    "paddle_tpu.framework.dataflow",
    "paddle_tpu.framework.memory_plan",
    "paddle_tpu.framework.ownership",
    "paddle_tpu.framework.sharding",
    "paddle_tpu.observability",
    "paddle_tpu.observability.tracing",
    "paddle_tpu.observability.metrics",
    "paddle_tpu.observability.ledger",
    "paddle_tpu.observability.flight_recorder",
    "paddle_tpu.observability.memory",
    "paddle_tpu.parallel",
    "paddle_tpu.parallel.collective",
    "paddle_tpu.parallel.elastic",
    "paddle_tpu.parallel.grad_comm",
    "paddle_tpu.parallel.pipeline",
    "paddle_tpu.parallel.process_world",
    "paddle_tpu.parallel.reshard",
    "paddle_tpu.data",
    "paddle_tpu.fusion",
]


import re

_ADDR = re.compile(r" at 0x[0-9a-fA-F]+")


def _sig(obj) -> str:
    try:
        s = str(inspect.signature(obj))
    except (ValueError, TypeError):
        return "(...)"
    # default values repr'ing with memory addresses are not stable
    return _ADDR.sub("", s)


def iter_api():
    import importlib
    for modname in PUBLIC_MODULES:
        mod = importlib.import_module(modname)
        names = getattr(mod, "__all__", None) or [
            n for n in vars(mod) if not n.startswith("_")]
        for name in sorted(set(names)):
            obj = getattr(mod, name, None)
            if obj is None or inspect.ismodule(obj):
                continue
            # only symbols defined inside the package
            owner = getattr(obj, "__module__", "") or ""
            if not owner.startswith("paddle_tpu"):
                continue
            # internal plumbing re-exported by accident is not public API
            # (places/flags under core ARE public; only helpers are not)
            if owner in ("paddle_tpu.core.enforce", "paddle_tpu.core.dtypes",
                         "paddle_tpu.core.unique_name"):
                continue
            if inspect.isclass(obj):
                yield f"{modname}.{name}{_sig(obj.__init__)}"
                for m_name, m in sorted(vars(obj).items()):
                    if m_name.startswith("_") or not callable(m):
                        continue
                    yield f"{modname}.{name}.{m_name}{_sig(m)}"
            elif callable(obj):
                yield f"{modname}.{name}{_sig(obj)}"


def main():
    lines = sorted(set(iter_api()))
    if "--update" in sys.argv:
        with open(os.path.join(REPO, "API.spec"), "w") as f:
            f.write("\n".join(lines) + "\n")
        print(f"wrote {len(lines)} signatures to API.spec")
    else:
        print("\n".join(lines))


if __name__ == "__main__":
    main()
