"""Trace-driven serving load harness: slot vs paged KV cache (ISSUE r20).

The capacity claim, measured end to end: at FIXED usable KV pool bytes,
on a long-tail + shared-prefix trace, the paged engine sustains >= 1.5x
the slot engine's admitted concurrency (equivalently <= 0.6x KV bytes
pinned per request) with decode output TOKEN-IDENTICAL per request, and
the pool accounting reconciles exactly (used + free == usable blocks)
after every run.

Both sides get the same KV token capacity:

- slot:  n_slots=4 rows x max_len=64         -> 256 reservable tokens
- paged: 32 data blocks x block_size=8 (+1 null block) -> 256 tokens,
         but 16 tick slots — a request pins ceil(L/8) blocks instead of
         a whole 64-token row, and shared prompt prefixes pin their
         blocks ONCE across the fan-out.

Traces (all committed): Poisson arrivals with a long-tail length mix;
a BURSTY trace — fan-out groups landing within a short burst window,
every member sharing one of a few long system prompts (the realistic
shape for the prefix cache: one agent template, N concurrent calls);
and a SATURATED trace (everything offered at t=0) that measures the
pool-limited admitted-concurrency ceiling directly. The engines run
the identical weights (one shared scope), greedy argmax, so the
per-request token streams must match bit-exact between engines — the
harness asserts it (the paged read path is the SAME attention chain
through a gather, fused by the same pass; tests/test_kv_pager.py pins
the program structure).

    JAX_PLATFORMS=cpu python tools/bench_serve_kv.py           # full, writes
                                                  BENCH_SERVE_KV_r20.json
    JAX_PLATFORMS=cpu python tools/bench_serve_kv.py --smoke   # CI stanza
"""

from __future__ import annotations

import gc
import json
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_DIMS = dict(vocab=1000, d_model=64, d_inner=128, num_heads=4,
             num_layers=2)
_MAX_LEN = 64
_BLOCK_SIZE = 8
_SLOT_SLOTS = 4                       # 4 x 64 = 256 reservable tokens
_PAGED_SLOTS = 16                     # tick width; blocks are the capacity
_PAGED_BLOCKS = _SLOT_SLOTS * _MAX_LEN // _BLOCK_SIZE + 1   # +1 null


def _trace(rng, n_requests, mean_interarrival_s, mode):
    """[(arrival_offset_s, prompt, max_new)]. Long-tail lengths; ~60%
    of requests extend one of 3 shared 16-token system prompts.
    Modes: "poisson" (exponential interarrivals), "bursty" (fan-out
    groups over one shared prefix, members spread across a short burst
    window — the prefix cache's target shape), "saturated" (the whole
    trace offered at t=0 — measures admitted-concurrency CAPACITY:
    with the backlog never empty, mean admitted concurrency is the
    engine's pool-limited ceiling, not the offered load)."""
    vocab = _DIMS["vocab"]
    prefixes = [rng.randint(0, vocab, 16).tolist() for _ in range(3)]
    out, t, i = [], 0.0, 0
    while i < n_requests:
        if mode == "bursty":
            t += float(rng.exponential(mean_interarrival_s * 5))
            fan = int(rng.randint(3, 7))
            pre = prefixes[rng.randint(len(prefixes))]
            group = [(pre, True)] * min(fan, n_requests - i)
        else:
            if mode == "poisson":
                t += float(rng.exponential(mean_interarrival_s))
            shared = bool(rng.rand() < 0.6)
            pre = prefixes[rng.randint(len(prefixes))] if shared else None
            group = [(pre, shared)]
        for j, (pre, shared) in enumerate(group):
            # burst members land ~20ms apart (a burst window, not one
            # instant) so the leader's prefill can seed the prefix
            # cache for its followers
            t_j = t + j * 0.02 if mode == "bursty" else t
            if shared:
                tail = rng.randint(0, vocab,
                                   int(rng.randint(2, 8))).tolist()
                prompt = list(pre) + tail
            else:
                plen = int(rng.choice([3, 4, 6, 8, 12, 20],
                                      p=[.2, .25, .2, .15, .1, .1]))
                prompt = rng.randint(0, vocab, plen).tolist()
            max_new = int(rng.choice([4, 6, 8, 16, 24],
                                     p=[.3, .25, .2, .15, .1]))
            max_new = min(max_new, _MAX_LEN - len(prompt))
            out.append((t_j, prompt, max_new))
            i += 1
    return out, prefixes


def _run_trace(kind, trace, prefixes, scope):
    """Replay one arrival trace (feeder thread, real clock) against a
    fresh engine; tick-level sampling of admitted concurrency."""
    from paddle_tpu.serving import ContinuousBatchingEngine, PagedKVEngine

    if kind == "slot":
        eng = ContinuousBatchingEngine(n_slots=_SLOT_SLOTS,
                                       max_len=_MAX_LEN, scope=scope,
                                       **_DIMS)
    else:
        eng = PagedKVEngine(n_slots=_PAGED_SLOTS, max_len=_MAX_LEN,
                            block_size=_BLOCK_SIZE,
                            n_blocks=_PAGED_BLOCKS, scope=scope, **_DIMS)
    # warm the compile, and seed the prefix cache with the system
    # prompts (both engines run the same warm-up for fairness; only
    # the paged engine's radix index retains anything from it)
    warm = [eng.submit([1], max_new=1)]
    warm += [eng.submit(list(p), max_new=1) for p in prefixes]
    eng.run_until_idle()
    assert all(r.done for r in warm)
    eng.n_ticks = eng.busy_slot_ticks = eng.total_slot_ticks = 0
    eng.tokens_out = 0
    if kind == "paged":
        eng.pager.n_admitted = eng.pager.prefix_hits = 0
        eng.pager.shared_blocks_total = 0
        eng.pager.blocks_allocated_total = 0
        eng.pager.evictions = eng.pager.cow_copies = 0

    order = []
    t0 = time.time()

    def feeder():
        for off, prompt, max_new in trace:
            delay = t0 + off - time.time()
            if delay > 0:
                time.sleep(delay)
            order.append(eng.submit(prompt, max_new))

    f = threading.Thread(target=feeder)
    f.start()
    done, active_curve, backlog_curve = [], [], []
    while f.is_alive() or eng.n_active or eng.n_pending:
        backlogged = eng.n_pending > 0     # admission ceiling binds
        finished = eng.step()
        done.extend(finished)
        n = eng.n_active
        if n:
            active_curve.append(n)
            if backlogged:
                backlog_curve.append(n)
        elif not eng.n_pending:
            time.sleep(0.001)
    f.join()
    makespan = time.time() - t0
    lats = sorted(r.latency_s for r in done)

    def pct(p):
        return lats[min(int(np.ceil(p * len(lats))) - 1, len(lats) - 1)]

    # KV bytes a request PINS: slot = the whole row, always; paged =
    # its privately allocated blocks (shared prefix blocks are the
    # saving — they are pinned once for the whole fan-out)
    kv_per_tok = eng._kv_bytes_static / (
        eng.n_slots * eng.max_len if kind == "slot"
        else eng.n_blocks * _BLOCK_SIZE)
    if kind == "slot":
        kv_bytes_per_req = eng.max_len * kv_per_tok
        pager_stats = None
        reconciles = True
    else:
        s = eng.pager.stats()
        kv_bytes_per_req = (s["blocks_per_request"] * _BLOCK_SIZE
                            * kv_per_tok)
        pager_stats = s
        eng.pager.pool.check()               # exact: used + free == N-1
        reconciles = (s["blocks_used"] + s["blocks_free"]
                      == eng.n_blocks - 1)
    curve = np.asarray(active_curve, np.float64)
    ds = max(1, len(curve) // 64)
    row = {
        "engine": kind,
        "n_requests": len(done),
        "tokens_per_sec": round(sum(len(r.tokens) for r in done)
                                / makespan, 1),
        "makespan_s": round(makespan, 3),
        "p50_latency_ms": round(pct(0.50) * 1e3, 1),
        "p95_latency_ms": round(pct(0.95) * 1e3, 1),
        "p99_latency_ms": round(pct(0.99) * 1e3, 1),
        "admitted_concurrency_mean": round(float(curve.mean()), 2),
        # mean over only the ticks where requests were WAITING — the
        # ticks where the admission ceiling (slots / pool blocks)
        # actually bound; the capacity ratio is computed on this
        "admitted_concurrency_under_backlog": round(
            float(np.mean(backlog_curve)), 2) if backlog_curve
            else round(float(curve.mean()), 2),
        "backlogged_ticks": len(backlog_curve),
        "admitted_concurrency_peak": int(curve.max()),
        "admitted_concurrency_curve": [round(float(x), 1) for x in
                                       curve[::ds][:64]],
        "kv_bytes_per_request": round(kv_bytes_per_req, 1),
        "kv_reserved_bytes": int(eng._kv_bytes_static),
        "occupancy": round(eng.occupancy(), 3),
        "census_reconciles": bool(reconciles),
    }
    if pager_stats is not None:
        row["pager"] = pager_stats
    tokens = [r.tokens for r in order]
    return row, tokens


def bench(n_requests=48, mean_interarrival_s=0.002, smoke=False):
    import paddle_tpu as pt

    if smoke:
        n_requests, mean_interarrival_s = 12, 0.001
    pt.reset_default_programs()
    pt.reset_global_scope()
    scope = pt.global_scope()          # both engines share one weight set
    rng = np.random.RandomState(20)
    runs = {}
    identical = True
    for tname, mode in (("poisson_longtail", "poisson"),
                        ("bursty_shared_prefix", "bursty"),
                        ("saturated_overload", "saturated")):
        trace, prefixes = _trace(rng, n_requests, mean_interarrival_s,
                                 mode)
        slot_row, slot_tokens = _run_trace("slot", trace, prefixes,
                                           scope)
        paged_row, paged_tokens = _run_trace("paged", trace, prefixes,
                                             scope)
        identical = identical and (slot_tokens == paged_tokens)
        conc = (paged_row["admitted_concurrency_under_backlog"]
                / max(slot_row["admitted_concurrency_under_backlog"],
                      1e-9))
        kvb = (paged_row["kv_bytes_per_request"]
               / max(slot_row["kv_bytes_per_request"], 1e-9))
        runs[tname] = {
            "slot": slot_row, "paged": paged_row,
            "decode_token_identical": bool(slot_tokens == paged_tokens),
            "paged_over_slot_admitted_concurrency": round(conc, 2),
            "paged_over_slot_kv_bytes_per_request": round(kvb, 3),
        }
    # the concurrency CAPACITY claim is anchored on the saturated
    # trace — on open-loop traces the paged engine often drains shared
    # -prefix bursts faster than they queue (prefill skipped), so its
    # sustained concurrency is bounded by offered load, not capacity
    cap_conc = runs["saturated_overload"][
        "paged_over_slot_admitted_concurrency"]
    worst_kvb = max(r["paged_over_slot_kv_bytes_per_request"]
                    for r in runs.values())
    out = {
        "bench": "serve_kv", "round": 20, "smoke": bool(smoke),
        "model": dict(_DIMS, max_len=_MAX_LEN),
        "fixed_pool": {
            "kv_token_capacity_both": _SLOT_SLOTS * _MAX_LEN,
            "slot": {"n_slots": _SLOT_SLOTS, "max_len": _MAX_LEN},
            "paged": {"n_tick_slots": _PAGED_SLOTS,
                      "block_size": _BLOCK_SIZE,
                      "n_blocks": _PAGED_BLOCKS,
                      "note": "n_blocks includes the reserved null "
                              "block (idle-slot write target); usable "
                              "data blocks = n_blocks - 1 = the slot "
                              "engine's exact token capacity"},
        },
        "n_requests_per_trace": n_requests,
        "runs": runs,
        "claims": {
            "decode_token_identical_all_traces": bool(identical),
            "paged_admitted_concurrency_ge_1p5x_at_saturation":
                bool(cap_conc >= 1.5),
            "paged_kv_bytes_per_request_le_0p6x_all_traces":
                bool(worst_kvb <= 0.6),
            "census_reconciles_used_plus_free_eq_reserved": bool(all(
                r["paged"]["census_reconciles"] for r in runs.values())),
        },
        "notes": "CPU-mesh measured. Admitted concurrency is sampled "
                 "per executed tick (mean over busy ticks). KV bytes "
                 "per request = bytes PINNED per admitted request: the "
                 "slot engine always pins one full max_len row; the "
                 "paged engine pins its privately allocated blocks "
                 "(shared prefix blocks pinned once per fan-out are "
                 "the saving). Token identity is asserted per request "
                 "across engines on identical weights (greedy argmax, "
                 "deterministic compute).",
    }
    return out


def _saturated_wall_s(scope, rng_seed, n_requests):
    """One saturated closed-loop run on a fresh paged engine: submit the
    whole trace at t=0, tick to idle, return (wall_s, ticks). The engine
    is rebuilt per call so the kv_sanitize flag state at CONSTRUCTION
    (attach-or-None) is what gets measured."""
    from paddle_tpu.serving import PagedKVEngine

    rng = np.random.RandomState(rng_seed)
    trace, prefixes = _trace(rng, n_requests, 0.0, "saturated")
    eng = PagedKVEngine(n_slots=_PAGED_SLOTS, max_len=_MAX_LEN,
                        block_size=_BLOCK_SIZE, n_blocks=_PAGED_BLOCKS,
                        scope=scope, **_DIMS)
    warm = [eng.submit([1], max_new=1)]
    eng.run_until_idle()
    assert all(r.done for r in warm)
    reqs = [eng.submit(list(p), max_new=m) for _, p, m in trace]
    # GC hygiene: collections triggered mid-run cost time proportional
    # to the WHOLE heap (which grows with every engine this process
    # built), and the sanitized state allocates more — without this the
    # "overhead" measured is mostly who paid for the next gen2 pause
    gc.collect()
    gc.disable()
    try:
        t0 = time.perf_counter()
        eng.run_until_idle(max_ticks=200000)
        wall = time.perf_counter() - t0
    finally:
        gc.enable()
    assert all(r.done for r in reqs)
    san = eng.pager.sanitizer
    return wall, eng.n_ticks, (san.stats() if san is not None else None)


def bench_sanitize(n_requests=48, repeats=10, smoke=False):
    """The r24 overhead budget for the shadow-state sanitizer, measured
    on the saturated-trace cell (the backlog never empties, so every
    tick carries a full slot set — the worst case for per-op shadow
    bookkeeping).

    Three states, engine rebuilt per run, best-of-`repeats` wall time:
    - `baseline`: kill switch off AND the per-tick engine hooks no-op'd
      (the pre-instrumentation tick loop);
    - `off`: kill switch off — shipped default. The only residue is the
      `pager.sanitizer is None` guard in the per-tick hooks, so the
      wall-clock delta vs baseline is pure noise; the committed 0.5%
      budget is therefore ALSO pinned by a deterministic micro-measure
      of the guard cost scaled to calls-per-tick;
    - `on`: kill switch on — full shadow mirroring + census.
    """
    import paddle_tpu as pt
    from paddle_tpu.core import flags
    from paddle_tpu.serving import PagedKVEngine

    if smoke:
        n_requests, repeats = 12, 3
    repeats = max(repeats, 3)          # rotation needs all three orders
    pt.reset_default_programs()
    pt.reset_global_scope()
    scope = pt.global_scope()

    def one(state):
        # one FIXED seed for every state and repeat: the trace (and so
        # the tick count and admit/release schedule) is identical
        # across cells, so wall time is directly comparable
        if state == "baseline":
            flags.set_flag("kv_sanitize", False)
            real = PagedKVEngine._note_tick_writes
            PagedKVEngine._note_tick_writes = lambda self, active: None
            try:
                return _saturated_wall_s(scope, 20, n_requests)
            finally:
                PagedKVEngine._note_tick_writes = real
        flags.set_flag("kv_sanitize", state == "on")
        return _saturated_wall_s(scope, 20, n_requests)

    # INTERLEAVED rounds, rotated order: run-to-run drift (scope/pool
    # growth, allocator state, CPU clocking) at this tick size is
    # larger than the sanitizer itself, so measuring each state's
    # repeats back-to-back would bias whichever state runs last —
    # every round visits all three states and the order rotates
    states = ("baseline", "off", "on")
    runs = {s: [] for s in states}
    one("baseline")                               # discard: cold caches
    for r in range(repeats):
        for s in states[r % 3:] + states[:r % 3]:
            runs[s].append(one(s))

    # the overhead claim compares per-state MINIMA: run-to-run noise
    # here is one-sided (scheduler/dispatch interference only ever ADDS
    # time — same state and seed swings +-30% while min-of-N is stable)
    # so the minimum over interleaved rounds converges on the true cost
    def med(xs):
        xs = sorted(xs)
        return xs[len(xs) // 2] if len(xs) % 2 else \
            0.5 * (xs[len(xs) // 2 - 1] + xs[len(xs) // 2])

    med_ratios = {
        s: round(med([runs[s][r][0] / runs["baseline"][r][0]
                      for r in range(repeats)]) - 1, 4)
        for s in ("off", "on")}

    def best(state):
        wall, ticks, stats = min(runs[state],
                                 key=lambda x: x[0] / max(x[1], 1))
        return {"wall_s": round(wall, 4), "ticks": ticks,
                "s_per_tick": round(wall / max(ticks, 1), 6),
                "sanitizer": stats}

    cells = {s: best(s) for s in states}
    flags.set_flag("kv_sanitize", False)

    # deterministic guard-cost micro-measure for the off budget: the
    # ONLY off-state residue is `san = pager.sanitizer; if san is None`
    # once per tick (plus one None-check per verify/resume event)
    class _P:
        sanitizer = None
    pager = _P()
    n_iter = 1_000_000
    t0 = time.perf_counter()
    for _ in range(n_iter):
        san = pager.sanitizer
        if san is not None:
            raise AssertionError
    guard_s = (time.perf_counter() - t0) / n_iter
    guard_per_tick = guard_s * 4          # hook + resume + verify + slack
    off_frac = guard_per_tick / cells["off"]["s_per_tick"]

    on_over = cells["on"]["wall_s"] / cells["baseline"]["wall_s"]
    off_over = cells["off"]["wall_s"] / cells["baseline"]["wall_s"]
    out = {
        "bench": "kv_sanitize_overhead", "round": 24, "smoke": bool(smoke),
        "model": dict(_DIMS, max_len=_MAX_LEN),
        "cell": {"trace": "saturated", "n_requests": n_requests,
                 "repeats_best_of": repeats},
        "cells": cells,
        "guard_cost_s": round(guard_s, 10),
        "overhead": {
            "on_vs_baseline_min": round(on_over - 1, 4),
            "off_vs_baseline_min": round(off_over - 1, 4),
            "median_paired_ratios": med_ratios,
            "off_guard_bound_frac": round(off_frac, 7),
        },
        "claims": {
            "sanitize_on_overhead_le_5pct": bool(on_over - 1 <= 0.05),
            "sanitize_off_guard_le_0p5pct": bool(off_frac <= 0.005),
        },
        "notes": "CPU-mesh measured. Overhead compares per-state "
                 "MINIMUM wall over interleaved rotated rounds on an "
                 "identical trace seed: run-to-run interference here "
                 "is one-sided (+-30% on identical runs) so the min "
                 "converges on the true cost where means/medians "
                 "cannot; median paired ratios are reported for "
                 "reference. The OFF "
                 "budget is additionally pinned by the deterministic "
                 "guard micro-measure (the only off-state residue is "
                 "one attribute load + None test per hook); the kill "
                 "switch is absence — with the flag off no wrapper is "
                 "installed (tests/test_ownership.py TestKillSwitch) "
                 "and the flag participates in the executor compile "
                 "cache key.",
    }
    return out


def main():
    smoke = "--smoke" in sys.argv
    if "--sanitize-overhead" in sys.argv:
        out = bench_sanitize(smoke=smoke)
        doc = json.dumps(out, indent=1)
        print(doc, flush=True)
        if not smoke:
            repo = os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))
            with open(os.path.join(repo, "BENCH_KV_SANITIZE_r24.json"),
                      "w") as f:
                f.write(doc + "\n")
        ok = out["claims"]
        assert ok["sanitize_off_guard_le_0p5pct"], \
            "sanitizer OFF guard cost exceeds the 0.5% budget"
        # the wall-clock ON budget is only meaningful at full scale —
        # smoke runs are ~40ms and the paired-median noise floor alone
        # is a few percent of that
        if not smoke:
            assert ok["sanitize_on_overhead_le_5pct"], \
                "sanitizer ON overhead exceeds the 5% budget"
        return
    out = bench(smoke=smoke)
    doc = json.dumps(out, indent=1)
    print(doc, flush=True)
    if not smoke:
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        with open(os.path.join(repo, "BENCH_SERVE_KV_r20.json"),
                  "w") as f:
            f.write(doc + "\n")
    ok = out["claims"]
    assert ok["decode_token_identical_all_traces"], \
        "paged decode diverged from the slot engine"
    assert ok["census_reconciles_used_plus_free_eq_reserved"], \
        "pool accounting did not reconcile"
    assert (ok["paged_admitted_concurrency_ge_1p5x_at_saturation"]
            or ok["paged_kv_bytes_per_request_le_0p6x_all_traces"]), \
        "paged engine met neither capacity bar"


if __name__ == "__main__":
    main()
