"""Per-phase HBM-traffic attribution of the flagship train step.

Parses the optimized HLO (dumped by tools/profile_resnet.py --exp buffer_census) and, for every
top-level instruction of the entry computation, charges
`sum(operand buffer bytes) + output bytes` — the fusion's real HBM traffic —
to a logical phase derived from its op_name metadata. Aliasing pseudo-ops
(get-tuple-element, bitcast, parameter, tuple) are skipped; async copy pairs
are counted once.

This is the per-buffer attribution table VERDICT r3 #1 asks for: each row is
checkable against the structural minimum for this program shape.

    python tools/attribute_bytes.py [/tmp/resnet_train_optimized.hlo]
"""

from __future__ import annotations

import collections
import json
import re
import sys

_IT = {"bf16": 2, "f16": 2, "f32": 4, "s32": 4, "u32": 4, "s8": 1,
       "u8": 1, "pred": 1, "s64": 8, "u64": 8}

_SKIP = {"get-tuple-element", "bitcast", "parameter", "tuple", "constant",
         "after-all", "copy-start", "async-start"}


def shape_bytes(sh: str) -> int:
    total = 0
    for m in re.finditer(r"(bf16|f32|f16|s32|u32|s8|u8|pred|s64|u64)"
                         r"\[([0-9,]*)\]", sh):
        n = 1
        for d in m.group(2).split(","):
            if d:
                n *= int(d)
        total += n * _IT[m.group(1)]
    return total


def classify(op: str, meta: str, shape: str) -> str:
    """Logical phase for one instruction, from opcode + jax op_name."""
    bwd = "transpose(" in meta
    if op in ("convolution", "custom-call") or "conv_general" in meta:
        if not bwd:
            return "conv_fwd"
        # jax emits conv dgrad as conv(dy, w) and wgrad as conv(x, dy);
        # metadata keeps the primitive name only, so split on output shape:
        # activation grads are [B, H, W, C] with square spatial dims;
        # weight grads are [Co, Ci, kh, kw] (kh==kw too, but tiny) — use
        # spatial size >= 7 as the activation signature. Tuple outputs
        # (weight-grad fused with the momentum update / BN-grad reductions)
        # classify by their first element.
        for dims in re.finditer(r"\[([0-9,]+)\]", shape):
            d = [int(x) for x in dims.group(1).split(",")]
            if len(d) == 4 and d[1] == d[2] and d[1] >= 7:
                return "conv_dgrad_fused"
        return "conv_wgrad_fused"
    if "select_and_scatter" in meta or op == "select-and-scatter":
        return "maxpool_bwd"
    if "reduce_window" in meta:
        return "maxpool_fwd"
    if op == "fusion" or op in ("add", "subtract", "multiply", "divide",
                                "maximum", "select", "compare", "convert",
                                "reduce", "broadcast", "rsqrt", "exponential",
                                "negate", "power", "sqrt", "scatter",
                                "dynamic-update-slice", "transpose", "copy",
                                "reshape", "slice", "concatenate", "pad",
                                "iota", "dot", "map", "reduce-precision"):
        if "sgd" in meta or "momentum" in meta or "adam" in meta \
                or "apply" in meta:
            return "optimizer"
        if "softmax" in meta or "cross_entropy" in meta or "log" in meta \
                or "one_hot" in meta or "mean" in meta and "pool" not in meta:
            return "loss_head"
        if "reduce_sum" in meta or "reduce(" in meta or "div" in meta \
                and bwd:
            return ("bn_or_reduce_bwd" if bwd else "bn_or_reduce_fwd")
        if op == "copy":
            return "layout_copy"
        if "dot" in meta or op == "dot":
            return "fc"
        return "elementwise_bwd" if bwd else "elementwise_fwd"
    if op in ("copy-done", "async-done"):
        # memory-space-assignment VMEM prefetch: the HBM read happens here
        # and the consumer then reads VMEM — the consumer's operand charge
        # double-counts this traffic, so keep it in its own bucket
        return "vmem_prefetch"
    if op in ("rng", "rng-bit-generator"):
        return "rng"
    return "other:" + op


def main():
    path = sys.argv[1] if len(sys.argv) > 1 else \
        "/tmp/resnet_train_optimized.hlo"
    hlo = open(path).read()

    # instruction name -> output bytes (for operand lookups), per computation
    cur = None
    defs = {}
    rows = []
    for line in hlo.splitlines():
        mc = re.match(r"(ENTRY )?%?([\w.\-]+)\s*\([^)]*\)\s*->", line)
        if mc:
            cur = "ENTRY" if mc.group(1) else mc.group(2)
            continue
        if cur != "ENTRY":
            continue
        m = re.match(r"\s+%?([\w.\-]+)\s*=\s*(\([^=]*?\)|\S+)\s+([a-z\-]+)",
                     line)
        if not m:
            continue
        name, sh, op = m.groups()
        out_b = shape_bytes(sh)
        defs[name] = (out_b, op)
        if op in _SKIP:
            continue
        # operand list: %names inside the first (...) after the opcode
        call = line[m.end():]
        operands = re.findall(r"%([\w.\-]+)", call.split("metadata")[0])
        in_b = 0
        seen = set()
        for o in operands:
            if o in seen or o not in defs:
                continue
            seen.add(o)
            ob, oop = defs[o]
            # reading through a get-tuple-element/bitcast charges the
            # element's own bytes (already its shape), fine as-is
            in_b += ob
        meta = ""
        mm = re.search(r'op_name="([^"]*)"', line)
        if mm:
            meta = mm.group(1)
        rows.append((in_b + out_b, op, sh, meta))

    buckets = collections.Counter()
    counts = collections.Counter()
    for b, op, sh, meta in rows:
        ph = classify(op, meta, sh)
        buckets[ph] += b
        counts[ph] += 1
    total = sum(buckets.values())
    print(json.dumps({
        "exp": "traffic_by_phase_GB",
        "total_GB": round(total / 1e9, 2),
        "phases": [(ph, round(bb / 1e9, 2), counts[ph])
                   for ph, bb in buckets.most_common()],
    }), flush=True)
    rows.sort(reverse=True)
    print(json.dumps({
        "exp": "top_instructions",
        "top25": [(round(b / 1e6), op, classify(op, meta, sh), sh[:44],
                   meta[:80]) for b, op, sh, meta in rows[:25]],
    }), flush=True)


if __name__ == "__main__":
    main()
