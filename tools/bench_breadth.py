"""Benchmark breadth: driver configs #3-#5 with the SAME audit fields as
the ResNet headline (VERDICT r2 #8; ≙ reference
benchmark/fluid/fluid_benchmark.py:299 printing throughput for all five
models).

Run on the real TPU and commit the output:

    env PYTHONPATH=/root/.axon_site:/root/repo \
        python tools/bench_breadth.py | tee BENCH_BREADTH_r03.json

Sync discipline: host-value realization of the last fetched loss is the
only trusted barrier through the remote tunnel (see bench.py).
"""

from __future__ import annotations

import json
import time

import numpy as np

_CHIP_SPECS = (("v5 lite", 197.0, 819.0), ("v5e", 197.0, 819.0),
               ("v5p", 459.0, 2765.0), ("v6", 918.0, 1640.0),
               ("v4", 275.0, 1228.0))


def _peak(dev):
    kind = (getattr(dev, "device_kind", "") or "").lower()
    for sub, p, _ in _CHIP_SPECS:
        if sub in kind:
            return p
    return None


def _hbm(dev):
    kind = (getattr(dev, "device_kind", "") or "").lower()
    for sub, _, h in _CHIP_SPECS:
        if sub in kind:
            return h
    return None


# every row names its binding bound so the artifact is self-interpreting
# (VERDICT r5 #4): mxu | hbm = roofline sides from XLA's own cost model;
# gather-bw = the scattered-row bandwidth bound (deepfm — its traffic IS
# the bound, the MXU is ~idle by design); tick-latency = the serialized
# per-tick kernel-latency floor (stacked_lstm — fraction_of_bound shows
# how far BELOW its roofline the latency floor pins it, the ROUND4
# attribution pulled into the artifact).
_BOUND_KIND = {
    "stacked_lstm": "tick-latency",
    "deepfm": "gather-bw",
}


def _bound_fields(name, step_ms, flops, bytes_acc, peak, hbm_gbps):
    if not (flops and peak):
        return {}
    ideal_mxu = flops / (peak * 1e12) * 1e3
    ideal_hbm = (bytes_acc / (hbm_gbps * 1e9) * 1e3
                 if bytes_acc and hbm_gbps else None)
    kind = next((v for k, v in _BOUND_KIND.items() if k in name), None)
    if kind is None:
        kind = ("hbm" if ideal_hbm and ideal_hbm > ideal_mxu else "mxu")
    binding = max(ideal_mxu, ideal_hbm or 0.0)
    return {
        "bound_kind": kind,
        "ideal_mxu_ms": round(ideal_mxu, 3),
        "ideal_hbm_ms_xla_bytes": (round(ideal_hbm, 3)
                                   if ideal_hbm else None),
        "fraction_of_bound": round(binding / step_ms, 3),
    }


def _measure(name, build, unit, iters=20):
    """build(rng) -> (loss_var, feed_or_feeds, units_per_step, optimizer).

    `feed_or_feeds` may be a list of distinct batches: the timed loop cycles
    through them so the model trains on a real dataset slice instead of
    memorizing one fixed batch (a fixed batch drives synthetic losses to 0.0
    inside the window, making the loss-decreased audit vacuous — VERDICT r3
    weak #4). All batches are staged to the device ONCE before timing: the
    timed window measures the training step, not the dev tunnel's ~17 MB/s
    host link (the ResNet headline bench stages the same way and measures
    the input pipeline separately via its prefetcher variant)."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt

    pt.reset_default_programs()
    pt.reset_global_scope()
    rng = np.random.RandomState(0)
    with pt.core.unique_name.guard():
        loss, feed, units, opt = build(rng)
        opt.minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())

    feeds = feed if isinstance(feed, list) else [feed]
    feeds = [{k: jnp.asarray(v) for k, v in f.items()} for f in feeds]
    k = len(feeds)

    out = exe.run(feed=feeds[0], fetch_list=[loss], return_numpy=False)
    float(np.asarray(out[0]).ravel()[0])  # compile + drain

    # best of 3 windows: the dev tunnel's effective throughput swings ~2x
    # with ambient load, so the fastest window is the least-interfered
    # estimate of the chip (losses tracked across ALL windows — training
    # continues through every one)
    losses, dt = [], None
    step_i = 0
    for _ in range(3):
        fetched = []
        t0 = time.time()
        for _ in range(iters):
            out = exe.run(feed=feeds[step_i % k], fetch_list=[loss],
                          return_numpy=False)
            fetched.append(out[0])
            step_i += 1
        float(np.asarray(fetched[-1]).ravel()[0])
        w = time.time() - t0
        dt = w if dt is None else min(dt, w)
        losses.extend(float(np.asarray(x).ravel()[0]) for x in fetched)

    ca = exe.cost_analysis(feed=feeds[0], fetch_list=[loss])
    flops = float(ca.get("flops", 0.0)) if ca else 0.0
    bytes_acc = float(ca.get("bytes accessed", 0.0)) if ca else 0.0
    dev = jax.devices()[0]
    peak = _peak(dev)
    implied = flops * iters / dt / 1e12 if flops else None
    rec = {
        "model": name,
        "value": round(units * iters / dt, 2),
        "unit": unit,
        "evidence": {
            "device_kind": getattr(dev, "device_kind", str(dev)),
            "step_ms": round(dt / iters * 1e3, 2),
            "flops_per_step_xla": flops,
            "implied_tflops": round(implied, 2) if implied else None,
            "mfu": (round(implied / peak, 4) if implied and peak else None),
            **_bound_fields(name, dt / iters * 1e3, flops, bytes_acc,
                            peak, _hbm(dev)),
            # first/last = mean over one full feed cycle, so the comparison
            # is over the same batches and batch-to-batch jitter cancels
            "loss_first": round(float(np.mean(losses[:k])), 4),
            "loss_last": round(float(np.mean(losses[-k:])), 4),
            "loss_decreased": bool(np.mean(losses[-k:]) < np.mean(losses[:k])
                                   and np.mean(losses[-k:]) > 0.0),
            "n_distinct_batches": k,
        },
    }
    print(json.dumps(rec), flush=True)
    return rec


def build_stacked_lstm(rng):
    import paddle_tpu as pt
    from paddle_tpu.models import stacked_lstm
    b, t = 64, 64
    loss, acc, _ = stacked_lstm.stacked_lstm_net(
        dict_dim=10000, emb_dim=256, hid_dim=256, max_len=t)
    # 8 distinct batches, labels = a real function of the sequence (token-sum
    # parity): learnable, so loss decreases, but 512 examples cannot be
    # memorized to 0.0 inside the timed window (VERDICT r3 weak #4)
    feeds = []
    for _ in range(8):
        words = rng.randint(0, 10000, (b, t)).astype("int64")
        label = (words.sum(axis=1, keepdims=True) % 2).astype("int64")
        feeds.append({"words": words,
                      "words@SEQLEN": np.full((b,), t, "int32"),
                      "label": label})
    opt = pt.optimizer.AdamOptimizer(learning_rate=5e-4)
    return loss, feeds, b * t, opt


def _markov_tokens(rng, b, t, vocab):
    """Sequences where tok[i+1] = (tok[i]*13 + 7 + eps) % vocab, eps∈[0,8):
    a 1st-order process any of the models here can learn, with a known
    entropy floor — distinct batches share the map, so descent is signal."""
    toks = np.empty((b, t), np.int64)
    toks[:, 0] = rng.randint(0, vocab, (b,))
    for i in range(1, t):
        toks[:, i] = (toks[:, i - 1] * 13 + 7
                      + rng.randint(0, 8, (b,))) % vocab
    return toks


def build_transformer(rng):
    import paddle_tpu as pt
    from paddle_tpu.models import transformer
    b, t = 16, 512
    loss, _ = transformer.transformer_lm(
        vocab=32000, max_len=t, d_model=512, d_inner=2048, num_heads=8,
        num_layers=6, dropout=0.0)   # dropout 0 -> flash-attention path
    # 4 distinct batches drawn from a learnable process: the next token is a
    # deterministic map of the current plus 3 bits of noise, so the CE floor
    # is ln(8)≈2.08 and descent reflects learning the map, not memorizing a
    # single fixed batch
    feeds = []
    for _ in range(4):
        toks = _markov_tokens(rng, b, t + 1, 32000)
        feeds.append({"tokens": toks[:, :-1].copy(),
                      "tokens@SEQLEN": np.full((b,), t, "int32"),
                      "targets": toks[:, 1:].copy()})
    opt = pt.optimizer.AdamOptimizer(learning_rate=1e-4)
    return loss, feeds, b * t, opt


def build_transformer_big(rng):
    """d_model=1024, 12 layers: a config whose arithmetic intensity sits
    ABOVE the v5e balance point — demonstrates the stack's MFU when the
    model shape permits it (the bs16·d512 line is HBM-intensity-capped at
    ~0.33 no matter the kernels; see tools/probe_lm.py)."""
    import paddle_tpu as pt
    from paddle_tpu.models import transformer
    b, t = 8, 1024
    loss, _ = transformer.transformer_lm(
        vocab=32000, max_len=t, d_model=1024, d_inner=4096, num_heads=16,
        num_layers=12, dropout=0.0)
    feeds = []
    for _ in range(2):
        toks = _markov_tokens(rng, b, t + 1, 32000)
        feeds.append({"tokens": toks[:, :-1].copy(),
                      "tokens@SEQLEN": np.full((b,), t, "int32"),
                      "targets": toks[:, 1:].copy()})
    opt = pt.optimizer.AdamOptimizer(learning_rate=1e-4)
    return loss, feeds, b * t, opt


def build_transformer_nmt(rng):
    import paddle_tpu as pt
    from paddle_tpu.models import transformer
    b, t = 16, 256
    loss, _ = transformer.transformer(
        src_vocab=16000, tgt_vocab=16000, max_len=t, d_model=512,
        d_inner=2048, num_heads=8, num_layers=4, dropout=0.0)
    # 4 distinct batches of a learnable translation task: tgt is a fixed
    # pointwise map of src ((src+5) mod V), lbl the next-token shift — the
    # decoder can learn it through cross-attention; no single batch to
    # memorize
    feeds = []
    for _ in range(4):
        src = _markov_tokens(rng, b, t + 1, 16000)
        tgt = (src + 5) % 16000
        feeds.append({"src": src[:, :-1].copy(),
                      "src@SEQLEN": np.full((b,), t, "int32"),
                      "tgt": tgt[:, :-1].copy(),
                      "tgt@SEQLEN": np.full((b,), t, "int32"),
                      "lbl": tgt[:, 1:].copy()})
    opt = pt.optimizer.AdamOptimizer(learning_rate=1e-4)
    return loss, feeds, b * t, opt


def build_deepfm(rng):
    import paddle_tpu as pt
    from paddle_tpu.models import deepfm
    b = 4096
    loss, _ = deepfm.deepfm(num_fields=39, vocab_size=1000000,
                            is_sparse=True, row_pad=128)
    # 8 distinct batches; each example's ids hit near-unique rows of the
    # 1M-row tables, so a single fixed batch is memorized through its own
    # embedding rows within a few visits — labels are instead a function of
    # the dense feature values (learnable through the shared MLP, not
    # memorizable through per-example rows)
    feeds = []
    for _ in range(8):
        vals = rng.rand(b, 39).astype("float32")
        label = (vals.mean(axis=1, keepdims=True) >
                 0.5).astype("float32")
        feeds.append({"feat_ids": rng.randint(0, 1000000,
                                              (b, 39)).astype("int64"),
                      "feat_vals": vals, "label": label})
    opt = pt.optimizer.AdamOptimizer(learning_rate=3e-4)
    return loss, feeds, b, opt


_RAGGED_T, _RAGGED_VOCAB = 512, 32000


def _ragged_corpus(rng, n_seqs=64):
    """Deterministic ragged corpus (~median length 100, up to T) shared by
    the packed and padded variants so the comparison is apples-to-apples."""
    lengths = np.clip((np.exp(rng.randn(n_seqs) * 0.6 + 4.6)).astype(int),
                      32, _RAGGED_T)
    seqs = [rng.randint(1, _RAGGED_VOCAB, (L,)).astype(np.int64)
            for L in lengths]
    real_tokens = int(sum(len(s) - 1 for s in seqs))  # trainable positions
    return seqs, real_tokens


def _build_ragged_lm(rng, packed, n_seqs=64):
    import paddle_tpu as pt
    from paddle_tpu.data.packing import pack_lm_batch
    from paddle_tpu.models import transformer

    seqs, real_tokens = _ragged_corpus(rng, n_seqs)
    T = _RAGGED_T
    loss, _ = transformer.transformer_lm(
        vocab=_RAGGED_VOCAB, max_len=T, d_model=512, d_inner=2048,
        num_heads=8, num_layers=6, dropout=0.0, packed=packed)
    if packed:
        feed = pack_lm_batch(seqs, T)
    else:
        rows = len(seqs)
        toks = np.zeros((rows, T), np.int64)
        tgts = np.zeros((rows, T), np.int64)
        sl = np.zeros((rows,), np.int32)
        for i, s in enumerate(seqs):
            toks[i, :len(s)] = s
            tgts[i, :len(s) - 1] = s[1:]
            sl[i] = len(s) - 1
        feed = {"tokens": toks, "tokens@SEQLEN": sl, "targets": tgts}
    opt = pt.optimizer.AdamOptimizer(learning_rate=1e-4)
    # `units` = REAL (non-pad) tokens: both variants share the numerator,
    # so value is directly comparable and the packed/padded ratio is the
    # padding waste eliminated (≙ the reference's LoD ragged batches whose
    # purpose is exactly not burning compute on padding)
    return loss, feed, real_tokens, opt


def measure_packed_vs_padded(iters=10):
    """The packed (segment-id) path's reason to exist: REAL tokens/sec on
    a ragged corpus, packed multi-sequence rows vs one padded sequence per
    row — full audit fields via the shared _measure harness."""
    packed = _measure("packed_ragged_lm_6l_512d_T512",
                      lambda rng: _build_ragged_lm(rng, True),
                      "real_tokens/sec", iters)
    padded = _measure("padded_ragged_lm_6l_512d_T512",
                      lambda rng: _build_ragged_lm(rng, False),
                      "real_tokens/sec", iters)
    # equal-ROW-COUNT packed run (4x corpus -> ~64 packed rows, the padded
    # run's row count): packing 64 sequences yields only ~16 rows, and a
    # 16-row program has lower MFU than a 64-row one on any path — this
    # line separates the segment-id kernel's true overhead from that
    # batch-size effect
    packed_eq = _measure("packed_ragged_lm_6l_512d_T512_eqrows",
                         lambda rng: _build_ragged_lm(rng, True, 256),
                         "real_tokens/sec", iters)
    print(json.dumps({
        "packed_over_padded_speedup":
            round(packed["value"] / padded["value"], 2),
        "packed_eqrows_mfu_over_padded_mfu":
            round(packed_eq["evidence"]["mfu"]
                  / padded["evidence"]["mfu"], 3)}), flush=True)
    return packed, padded, packed_eq


def main():
    import jax
    on_accel = jax.devices()[0].platform != "cpu"
    iters = 20 if on_accel else 2
    recs = [
        _measure("stacked_lstm_bs64_T64", build_stacked_lstm,
                 "tokens/sec", iters),
        _measure("transformer_lm_6l_512d_bs16_T512_flash",
                 build_transformer, "tokens/sec", iters),
        _measure("transformer_lm_12l_1024d_bs8_T1024_flash",
                 build_transformer_big, "tokens/sec", iters),
        _measure("transformer_nmt_4l_512d_bs16_T256_flash",
                 build_transformer_nmt, "tokens/sec", iters),
        _measure("deepfm_bs4096_vocab1M_sparse", build_deepfm,
                 "examples/sec", iters),
    ]
    recs.extend(measure_packed_vs_padded(iters=10 if on_accel else 1))
    ok = all(r["evidence"]["loss_decreased"] for r in recs)
    print(json.dumps({"all_losses_decreased": ok}), flush=True)


if __name__ == "__main__":
    main()
