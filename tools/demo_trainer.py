#!/usr/bin/env python
"""Train a SAVED program with no model-building code.

≙ reference paddle/fluid/train/demo/demo_trainer.cc:55-80 — the pure-C++
trainer that loads a serialized startup+main ProgramDesc and loops
`executor.Run(main)`. The capability being demonstrated is identical:
training is fully described by the serialized program; the driver knows
nothing about the model. (The reference's driver is C++ because its
executor is C++; here the executor is the XLA runtime, reached through the
thin python shim — the native layer below it is XLA/Mosaic itself.)

Usage:
    # save a program from any model script:
    #   pt.io.save_program(dir, feed_names=[...], fetch_names=[loss])
    python tools/demo_trainer.py --model_dir DIR --iters 10 --batch_size 8
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def synth_feed(program, feed_names, batch_size, seed=0):
    """Synthesize feed arrays from the program's declared var shapes
    (≙ the demo's fake data)."""
    import numpy as np
    rng = np.random.RandomState(seed)
    feed = {}
    blk = program.global_block()
    for name in feed_names:
        var = blk.var(name)
        shape = [batch_size if int(d) == -1 else int(d)
                 for d in (var.shape or [])]
        dname = var.dtype.name if hasattr(var.dtype, "name") else str(var.dtype)
        if "int" in dname:
            feed[name] = rng.randint(0, 2, size=shape).astype(dname)
        else:
            feed[name] = rng.rand(*shape).astype(dname)
    return feed


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model_dir", required=True)
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--batch_size", type=int, default=8)
    args = p.parse_args()

    import paddle_tpu as pt

    main_prog, startup_prog, feed_names, fetch_names = \
        pt.io.load_program(args.model_dir)
    exe = pt.Executor()
    exe.run(startup_prog)

    feed = synth_feed(main_prog, feed_names, args.batch_size)
    for i in range(args.iters):
        vals = exe.run(main_prog, feed=feed, fetch_list=fetch_names)
        line = " ".join(f"{n}={float(v.reshape(-1)[0]):.6f}"
                        for n, v in zip(fetch_names, vals))
        print(f"iter {i}: {line}")
    print(json.dumps({"status": "ok", "iters": args.iters,
                      "fetches": fetch_names}))


if __name__ == "__main__":
    main()
