#!/usr/bin/env python
"""Weight-only quantized serving + zero-dispatch tick benchmark.

One artifact, five measurements (the r21 perf round's evidence):

  a. quant census — f32 vs int8 vs int4 param bytes for the serving tick
     program, with the `params_quantized` category reconciled EXACTLY
     against the planner's predicted `memory_categories` (the ledger
     identity: predicted == hand-summed payload+scale nbytes == measured
     `state_census`).
  b. token parity — greedy decode f32 vs int8 vs int4 on shared weights:
     per-request first-divergence index plus the max first-tick logit
     error (the quantization noise that flips near-tie argmaxes).
  c. dispatch A/B — the prepared tick's per-tick dict path
     (`PreparedStep.run`) vs the donated bound path
     (`PreparedStep.run_bound`) at PROBE_GAP_r07's
     serve_tick_lm2l_64d_8slots config, plus per-tick Python allocation
     bytes (tracemalloc) for both paths and the live engine's `dispatch`
     span share — compared against r07's 19.1% dispatch-saved baseline.
  d. KV headroom — the HBM bytes freed by weight quantization converted
     into extra BlockPool blocks at a FIXED total budget; admitted
     concurrency under backlog measured on the saturated arrival trace
     (bench_serve_kv machinery), f32 pool vs quantized+enlarged pool.
  e. r05 re-measure — the open BENCH_GEN_r05 bs16 regression
     (greedy −5%, beam-4 −13% vs r04) re-run on the CURRENT fused decode
     path at the original lm6l_512d_bs16_gen64 config, fused off/on,
     with a plain statement on whether it still regresses on this mesh.

    env JAX_PLATFORMS=cpu PYTHONPATH=/root/repo \
        python tools/bench_qserve.py | tee BENCH_QSERVE_r21.json

`--smoke` shrinks trace sizes/iteration counts and skips the full-dim
r05 section (CI wiring); `--section a,c` runs a subset. On a
non-accelerator host JAX executes synchronously, so the dispatch window
(tick start → run_bound return) spans the whole computation — section c
reports that honestly instead of claiming an async overlap win.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
import tracemalloc

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))

# PROBE_GAP_r07's serve-tick config (serve_tick_lm2l_64d_8slots): the
# dispatch baseline was measured here, so the A/B re-measures here
_DIMS = dict(vocab=1000, d_model=64, d_inner=128, num_heads=4,
             num_layers=2)
_MAX_LEN = 64
_SLOTS = 8
# PROBE_GAP_r07.json vs_executor_run at that config: prepared 1.088 ms,
# run 1.345 ms -> 19.1% of the per-tick wall was per-call dispatch
_R07 = dict(prepared_tick_ms=1.088, run_tick_ms=1.345,
            dispatch_saved_pct=19.1)
# BENCH_GEN_r05.json committed rows (the open bs16 regression: vs_r04
# recorded bs16_greedy 10877 -> 10360, bs16_beam4 5951 -> 5169)
_R05 = dict(bs16_greedy_tokens_per_sec=10360.5,
            bs16_beam4_tokens_per_sec=5169.3,
            r04_bs16_greedy_tokens_per_sec=10877.0,
            r04_bs16_beam4_tokens_per_sec=5951.0)


def _fresh_scope():
    import paddle_tpu as pt
    pt.reset_default_programs()
    pt.reset_global_scope()
    return pt.global_scope()


def _trainable_names(eng):
    return sorted(n for n, v in eng._program.current_block().vars.items()
                  if v.persistable and getattr(v, "trainable", False))


def _snapshot(eng):
    return {n: np.asarray(eng.scope.get(n)).copy()
            for n in _trainable_names(eng)}


def _restore(scope, snap):
    for n, a in snap.items():
        scope.set_var(n, a)


def _gen(eng, prompts, max_new=8):
    reqs = [eng.submit(list(p), max_new=max_new) for p in prompts]
    eng.run_until_idle()
    return [list(r.tokens) for r in reqs]


def _first_divergence(a, b):
    for i, (x, y) in enumerate(zip(a, b)):
        if x != y:
            return i
    return None if len(a) == len(b) else min(len(a), len(b))


def _tick_logits(eng, tok_id=7):
    """Run ONE tick of the engine's compiled program fetching the lm_head
    logits (the argmax input) for slot 0 consuming `tok_id` at pos 0."""
    name = None
    for op in eng._program.current_block().ops:
        if op.type == "arg_max":
            name = op.inputs["X"][0]
    assert name is not None
    feed = {k: v.copy() for k, v in eng._feeds.items()}
    feed["tick_tok"][0, 0] = tok_id
    out = eng._exe.run(eng._program, feed=feed, fetch_list=[name],
                       scope=eng.scope)
    # that run donated the engine's cache buffers and wrote fresh ones to
    # the scope — re-pin the bound tick (bind contract: state replaced in
    # the scope -> bind again)
    eng._step.bind(eng._feeds)
    return np.asarray(out[0])[0, 0].astype(np.float64)


# -- a + b: census / ledger identity and token parity ----------------------

def bench_quant_census_and_parity(smoke=False):
    from paddle_tpu.framework.costs import memory_categories
    from paddle_tpu.observability.memory import state_census
    from paddle_tpu.serving import ContinuousBatchingEngine

    def census_row(kind, eng, f32):
        # measured at BUILD time: the shared scope holds THIS engine's
        # payloads right now; a later engine's pass overwrites them (the
        # bound steps keep serving from their pinned arrays regardless)
        prog = eng._program
        pred = memory_categories(prog)
        names = [n for n, v in prog.current_block().vars.items()
                 if v.persistable]
        meas = state_census(scope, prog, names)["categories"]
        hand = 0
        for n in names:
            if n.endswith("@qparam") or n.endswith("@qscale"):
                hand += int(np.asarray(scope.get(n)).nbytes)
        pq_pred = int(pred.get("params_quantized", 0))
        pq_meas = int(meas.get("params_quantized", 0))
        return {
            "engine": kind,
            "params_bytes_f32": int(f32.params_bytes_f32),
            "params_bytes": int(eng._param_bytes()),
            "ratio_vs_f32": round(f32.params_bytes_f32
                                  / max(eng._param_bytes(), 1), 3),
            "quant_freed_bytes": int(eng.quant_freed_bytes),
            "params_quantized_predicted": pq_pred,
            "params_quantized_hand_summed": hand,
            "params_quantized_measured": pq_meas,
            "ledger_identity_exact": pq_pred == hand == pq_meas,
            "params_predicted": int(pred.get("params", 0)),
            "params_measured": int(meas.get("params", 0)),
            "params_identity_exact":
                int(pred.get("params", 0)) == int(meas.get("params", 0)),
        }

    scope = _fresh_scope()
    engines, rows, logits = {}, [], {}
    f32 = ContinuousBatchingEngine(n_slots=_SLOTS, max_len=_MAX_LEN,
                                   scope=scope, cache_prefix="bq_f32",
                                   **_DIMS)
    engines["f32"] = f32
    logits["f32"] = _tick_logits(f32)
    rows.append(census_row("f32", f32, f32))
    snap = _snapshot(f32)
    for kind in ("int8", "int4"):
        _restore(scope, snap)
        eng = ContinuousBatchingEngine(
            n_slots=_SLOTS, max_len=_MAX_LEN, scope=scope,
            cache_prefix=f"bq_{kind[-1]}", quant=kind, **_DIMS)
        engines[kind] = eng
        logits[kind] = _tick_logits(eng)
        rows.append(census_row(kind, eng, f32))

    # token parity on the SHARED weights: every engine decodes the same
    # prompts; first divergence index per request + first-tick logit error
    rng = np.random.RandomState(7)
    n_prompts = 4 if smoke else 12
    prompts = [rng.randint(0, _DIMS["vocab"], rng.randint(1, 6)).tolist()
               for _ in range(n_prompts)]
    ref = _gen(engines["f32"], prompts)
    ref_logits = logits["f32"]
    parity = {}
    for kind in ("int8", "int4"):
        got = _gen(engines[kind], prompts)
        div = [_first_divergence(r, g) for r, g in zip(ref, got)]
        err = np.abs(logits[kind] - ref_logits)
        parity[kind] = {
            "n_requests": len(prompts),
            "token_identical_requests": sum(d is None for d in div),
            "first_divergence_index": [d for d in div],
            "max_first_tick_logit_err": round(float(err.max()), 5),
            "logit_err_rel_to_range": round(
                float(err.max() / (ref_logits.max() - ref_logits.min())),
                5),
            "first_tick_argmax_matches":
                bool(int(np.argmax(logits[kind]))
                     == int(np.argmax(ref_logits))),
        }
    parity["note"] = (
        "untrained random weights at vocab=1000: logits are near-uniform, "
        "so quantization noise of order logit_err_rel_to_range flips "
        "near-tie argmaxes after a few ticks. tests/test_quant_serving.py "
        "pins int8 token-IDENTICAL greedy decode at vocab=50; int4 is "
        "bounded by the per-tile error |w-deq| <= scale/2.")
    return rows, parity


# -- c: dispatch A/B -------------------------------------------------------

def _best_of(fn, iters, windows=3):
    best = None
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn()
        np.asarray(out[0])        # host realization barrier
        dt = (time.perf_counter() - t0) / iters
        best = dt if best is None else min(best, dt)
    return best


def _alloc_per_tick(fn, iters):
    """Python-heap bytes newly allocated per tick (tracemalloc snapshot
    diff over `iters` ticks) — the zero-dispatch claim's host-side half."""
    fn()
    tracemalloc.start()
    s0 = tracemalloc.take_snapshot()
    for _ in range(iters):
        out = fn()
    np.asarray(out[0])
    s1 = tracemalloc.take_snapshot()
    tracemalloc.stop()
    grew = sum(max(d.size_diff, 0) for d in s1.compare_to(s0, "filename"))
    return grew / iters


def bench_dispatch(smoke=False):
    from paddle_tpu.core import flags
    from paddle_tpu.observability import tracing
    from paddle_tpu.serving import ContinuousBatchingEngine

    scope = _fresh_scope()
    eng = ContinuousBatchingEngine(n_slots=_SLOTS, max_len=_MAX_LEN,
                                   scope=scope, cache_prefix="bq_disp",
                                   quant="int8", **_DIMS)
    step, feeds = eng._step, eng._feeds
    plain = lambda: step.run(dict(feeds))     # noqa: E731 — per-tick dict
    bound = lambda: step.run_bound()          # noqa: E731 — donated state
    plain()
    iters = 30 if smoke else 300
    run_ms = _best_of(plain, iters) * 1e3
    bound_ms = _best_of(bound, iters) * 1e3
    alloc_iters = 20 if smoke else 100
    row = {
        "config": "serve_tick_lm2l_64d_8slots_int8",
        "run_tick_ms": round(run_ms, 4),
        "bound_tick_ms": round(bound_ms, 4),
        "dispatch_saved_ms": round(run_ms - bound_ms, 4),
        "dispatch_saved_pct": round(100 * (run_ms - bound_ms)
                                    / max(run_ms, 1e-9), 1),
        "alloc_bytes_per_tick_run": round(_alloc_per_tick(plain,
                                                          alloc_iters), 1),
        "alloc_bytes_per_tick_bound": round(_alloc_per_tick(bound,
                                                            alloc_iters), 1),
        "baseline_r07": _R07,
    }

    # live engine: the `dispatch` span (tick start -> run_bound return)
    # as a share of the whole tick, from the engine's own histograms
    old = flags.get_flag("trace")
    flags.set_flag("trace", True)
    try:
        mark = tracing.mark()
        rng = np.random.RandomState(3)
        n = 8 if smoke else 32
        for _ in range(2):
            reqs = [eng.submit(rng.randint(0, _DIMS["vocab"],
                                           rng.randint(1, 5)).tolist(),
                               max_new=8) for _ in range(n)]
            eng.run_until_idle()
            assert all(r.done for r in reqs)
        spans = [s for s in tracing.spans_since(mark)
                 if s.kind == "dispatch"]
    finally:
        flags.set_flag("trace", old)
    d50 = eng._m_dispatch.quantile(0.5) or 0.0
    t50 = eng._m_tick_latency.quantile(0.5) or 0.0
    row.update({
        "dispatch_span_count": len(spans),
        "engine_dispatch_ms_p50": round(d50 * 1e3, 4),
        "engine_tick_ms_p50": round(t50 * 1e3, 4),
        "engine_dispatch_share_pct": round(100 * d50 / max(t50, 1e-12), 1),
        "note": (
            "CPU mesh executes synchronously: run_bound() returns only "
            "after the computation finishes, so the dispatch span covers "
            "compute and its share cannot drop below ~100% here — the "
            "honest win on this mesh is run_tick_ms -> bound_tick_ms "
            "(per-tick argument marshalling removed) and the per-tick "
            "Python allocation floor. On TPU the same span measures true "
            "async-dispatch cost against r07's 19.1% baseline."),
    })
    return row


# -- d: freed HBM -> BlockPool headroom -> admitted concurrency ------------

def bench_kv_headroom(smoke=False):
    from bench_serve_kv import _trace
    from paddle_tpu.serving import PagedKVEngine

    block_size = 8
    base_blocks = 33                  # the r20 bench_serve_kv pool
    n_req = 16 if smoke else 48
    rng = np.random.RandomState(11)
    trace, prefixes = _trace(rng, n_req, 0.001, "saturated")

    def run(quant, n_blocks, scope):
        eng = PagedKVEngine(n_slots=16, max_len=_MAX_LEN,
                            block_size=block_size, n_blocks=n_blocks,
                            scope=scope, quant=quant, **_DIMS)
        warm = [eng.submit([1], max_new=1)]
        warm += [eng.submit(list(p), max_new=1) for p in prefixes]
        eng.run_until_idle()
        assert all(r.done for r in warm)
        eng.n_ticks = eng.busy_slot_ticks = eng.total_slot_ticks = 0
        t0 = time.time()
        order = []

        def feeder():
            for off, prompt, max_new in trace:
                delay = t0 + off - time.time()
                if delay > 0:
                    time.sleep(delay)
                order.append(eng.submit(prompt, max_new))

        f = threading.Thread(target=feeder)
        f.start()
        done, backlog_curve = [], []
        while f.is_alive() or eng.n_active or eng.n_pending:
            backlogged = eng.n_pending > 0
            done.extend(eng.step())
            if eng.n_active and backlogged:
                backlog_curve.append(eng.n_active)
            elif not eng.n_active and not eng.n_pending:
                time.sleep(0.001)
        f.join()
        makespan = time.time() - t0
        eng.pager.pool.check()
        return eng, {
            "quant": quant or "f32",
            "n_blocks": n_blocks,
            "params_bytes": int(eng._param_bytes()),
            "pool_bytes": int(eng._kv_bytes_static),
            "hbm_budget_bytes": int(eng._param_bytes()
                                    + eng._kv_bytes_static),
            "n_requests": len(done),
            "tokens_per_sec": round(sum(len(r.tokens) for r in done)
                                    / makespan, 1),
            "admitted_concurrency_under_backlog": round(
                float(np.mean(backlog_curve)), 2) if backlog_curve
                else None,
            "backlogged_ticks": len(backlog_curve),
        }

    scope = _fresh_scope()
    base_eng, base_row = run(None, base_blocks, scope)
    block_bytes = base_eng._kv_bytes_static / base_eng.n_blocks
    # fixed-HBM conversion: quantize weights on a throwaway engine to get
    # the freed bytes, then hand EXACTLY those bytes back as pool blocks
    scope = _fresh_scope()
    probe = PagedKVEngine(n_slots=16, max_len=_MAX_LEN,
                          block_size=block_size, n_blocks=base_blocks,
                          scope=scope, quant="int8", **_DIMS)
    extra = int(probe.quant_freed_bytes // block_bytes)
    scope = _fresh_scope()
    _, q_row = run("int8", base_blocks + extra, scope)
    return {
        "trace": "saturated",
        "block_bytes": int(block_bytes),
        "quant_freed_bytes": int(probe.quant_freed_bytes),
        "extra_blocks_at_fixed_hbm": extra,
        "f32": base_row,
        "int8": q_row,
        "admitted_concurrency_gain": (
            round(q_row["admitted_concurrency_under_backlog"]
                  / base_row["admitted_concurrency_under_backlog"], 2)
            if base_row["admitted_concurrency_under_backlog"]
            and q_row["admitted_concurrency_under_backlog"] else None),
    }


# -- e: r05 bs16 regression re-measure -------------------------------------

def _measure_decode(fuse, batch, gen_len, beam, iters, windows=2):
    import paddle_tpu as pt
    from paddle_tpu.core import flags, unique_name
    from paddle_tpu.models import transformer

    pt.reset_default_programs()
    pt.reset_global_scope()
    old = flags.get_flag("fuse_decode_attention")
    flags.set_flag("fuse_decode_attention", fuse)
    try:
        with unique_name.guard():
            seqs, _ = transformer.transformer_lm_generate(
                vocab=32000, max_gen=gen_len, d_model=512, d_inner=2048,
                num_heads=8, num_layers=6, bos_id=1, beam_size=beam)
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        feed = {"prompt": np.full((batch, 1), 1, "int64")}
        run = lambda: exe.run(feed=feed, fetch_list=[seqs])[0]  # noqa
        np.asarray(run())            # compile + drain
        best = None
        for _ in range(windows):
            t0 = time.perf_counter()
            for _ in range(iters):
                out = run()
            np.asarray(out)
            dt = (time.perf_counter() - t0) / iters
            best = dt if best is None else min(best, dt)
    finally:
        flags.set_flag("fuse_decode_attention", old)
    return dict(tokens_per_sec=round(batch * gen_len / best, 1),
                ms_per_step=round(best / gen_len * 1e3, 3))


def bench_r05_remeasure(iters=2):
    import jax
    rows = {}
    for label, beam in (("bs16_greedy", 1), ("bs16_beam4", 4)):
        for fuse in (False, True):
            key = f"{label}_{'fused' if fuse else 'unfused'}"
            rows[key] = _measure_decode(fuse, 16, 64, beam, iters)
    g_now = rows["bs16_greedy_fused"]["tokens_per_sec"]
    b_now = rows["bs16_beam4_fused"]["tokens_per_sec"]
    g_fuse_pct = round(100 * (g_now / rows["bs16_greedy_unfused"]
                              ["tokens_per_sec"] - 1), 1)
    b_fuse_pct = round(100 * (b_now / rows["bs16_beam4_unfused"]
                              ["tokens_per_sec"] - 1), 1)
    dev = getattr(jax.devices()[0], "device_kind", str(jax.devices()[0]))
    g_state = ("the bs16 greedy regression is still present in sign here"
               if g_fuse_pct < 0 else
               "the bs16 greedy regression does not reproduce here")
    b_state = ("the bs16 beam-4 regression is still present in sign here"
               if b_fuse_pct < 0 else
               "the bs16 beam-4 regression does not reproduce here")
    rows.update({
        "config": "lm6l_512d_bs16_gen64 (the BENCH_GEN_r05 shapes)",
        "device_kind": dev,
        "baseline_device_kind": "TPU v5 lite",
        "baseline_r05": _R05,
        "fusion_delta_pct": {"bs16_greedy": g_fuse_pct,
                             "bs16_beam4": b_fuse_pct},
        "statement": (
            f"BENCH_GEN_r05's open bs16 regression (greedy 10877->10360, "
            f"beam4 5951->5169 tok/s vs r04) was measured on TPU v5 "
            f"lite; this run is on {dev}, so absolute tokens/s are NOT "
            f"comparable ({g_now} greedy / {b_now} beam4 here). What "
            f"this mesh can answer is the fused-vs-unfused sign at the "
            f"same shapes on the current dynamic-update-slice decode: "
            f"bs16 greedy fused is {g_fuse_pct:+.1f}% vs unfused — "
            f"{g_state} — and bs16 beam4 fused is {b_fuse_pct:+.1f}% — "
            f"{b_state}. The absolute r05-vs-r04 bs16 question stays "
            f"OPEN pending a TPU re-run; this mesh cannot close it."),
    })
    return rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny traces/iters; skips the full-dim r05 "
                         "section (CI wiring)")
    ap.add_argument("--section", default="a,c,d,e",
                    help="comma list from {a,c,d,e}; a covers census AND "
                         "parity (b)")
    args = ap.parse_args()
    want = set(args.section.split(","))
    out = {"bench": "qserve", "smoke": bool(args.smoke)}
    if "a" in want or "b" in want:
        census, parity = bench_quant_census_and_parity(args.smoke)
        out["quant_census"] = census
        out["token_parity"] = parity
    if "c" in want:
        out["dispatch"] = bench_dispatch(args.smoke)
    if "d" in want:
        out["kv_headroom"] = bench_kv_headroom(args.smoke)
    if "e" in want and not args.smoke:
        out["r05_remeasure"] = bench_r05_remeasure()
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
