"""Probe: flash fwd+bwd at T=8k/16k/32k across block configs, with the
causal block-skip landed. Interleaved rounds per T (tunnel drift).

    env PYTHONPATH=/root/.axon_site:/root/repo python tools/probe_flash_blocks.py
"""
import json
import sys
import time

import numpy as np


def _realize(x):
    return float(np.asarray(x).ravel()[0])


def _attn_flops(b, h, t, d):
    return 3.5 * (2 * 2 * b * h * t * t * d) * 0.5


def _runner(T, bq, bk, b=1, h=8, d=128, reps=3):
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops import pallas_kernels as pk

    rng = np.random.RandomState(0)
    shape = (b, h, T, d)
    q = jnp.asarray(rng.randn(*shape).astype(np.float32), dtype=jnp.bfloat16)
    k = jnp.asarray(rng.randn(*shape).astype(np.float32), dtype=jnp.bfloat16)
    v = jnp.asarray(rng.randn(*shape).astype(np.float32), dtype=jnp.bfloat16)

    def loss(q, k, v):
        out = pk.flash_attention(q, k, v, causal=True, block_q=bq,
                                 block_k=bk)
        return jnp.sum(out.astype(jnp.float32))

    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
    try:
        out = g(q, k, v)
        _realize(out[0][0, 0, 0, 0])
    except Exception as e:
        return None, f"failed: {type(e).__name__}: {e!s:.80}"

    def run():
        t0 = time.time()
        for _ in range(reps):
            out = g(q, k, v)
        _realize(out[0][0, 0, 0, 0])
        return (time.time() - t0) / reps
    return run, None


def main():
    configs = [(512, 1024), (1024, 1024), (1024, 2048), (2048, 1024),
               (512, 2048)]
    for T in (8192, 16384, 32768):
        runners = {}
        for bq, bk in configs:
            r, err = _runner(T, bq, bk)
            if r is None:
                print(json.dumps({"T": T, "cfg": [bq, bk], "err": err}),
                      flush=True)
            else:
                runners[(bq, bk)] = r
        best = {c: None for c in runners}
        for _ in range(3):
            for c, r in runners.items():
                dt = r()
                best[c] = dt if best[c] is None else min(best[c], dt)
        fl = _attn_flops(1, 8, T, 128)
        print(json.dumps({
            "T": T,
            "results": {f"{c[0]}x{c[1]}":
                        {"ms": round(v * 1e3, 2),
                         "attn_tflops": round(fl / v / 1e12, 1)}
                        for c, v in best.items()},
        }), flush=True)


if __name__ == "__main__":
    main()
